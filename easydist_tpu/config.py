"""Flat env-var-driven configuration (reference: easydist/config.py:28-126).

Every knob is a module global, overridable by environment variable at import
time and mutated by API kwargs at runtime.  Imported everywhere as `edconfig`.
"""

import logging
import os


def _env_bool(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


# ---------------- logging / dumps ----------------
log_level = getattr(logging, os.environ.get("EASYDIST_LOGLEVEL", "INFO").upper())
dump_dir = os.environ.get("EASYDIST_DUMP_DIR", None)
dump_strategy = _env_bool("EASYDIST_DUMP_STRATEGY", True)
dump_cluster = _env_bool("EASYDIST_DUMP_CLUSTER", False)
# graphviz DOT of the MetaIR graph with chosen placements (resharding
# edges highlighted) — reference DUMP_FX_GRAPH, compile_auto.py:487-508
dump_graphviz = _env_bool("EASYDIST_DUMP_GRAPHVIZ", True)
# optimized-HLO text of each compiled executable (what GSPMD emitted)
dump_hlo = _env_bool("EASYDIST_DUMP_HLO", False)

# ---------------- compile cache ----------------
enable_compile_cache = _env_bool("EASYDIST_COMPILE_CACHE", False)
compile_cache_dir = os.environ.get("EASYDIST_COMPILE_CACHE_DIR", "./.easydist_cache")

# ---------------- ShardCombine discovery ----------------
# number of shards used when executing candidate shardings (reference
# metashard/metaop.py:62 uses 2)
discovery_nshards = _env_int("EASYDIST_DISCOVERY_NSHARDS", 2)
# run discovery ops on CPU even when a TPU is present (device dispatch for
# thousands of tiny eager ops is wasteful; discovery is compile-time analysis)
discovery_on_cpu = _env_bool("EASYDIST_DISCOVERY_ON_CPU", True)
# allclose tolerance for recombination checks (reference platform/jax.py:24
# uses rtol 5e-3 because of tf32; we default tighter on CPU float32)
allclose_rtol = _env_float("EASYDIST_ALLCLOSE_RTOL", 1e-3)
allclose_atol = _env_float("EASYDIST_ALLCLOSE_ATOL", 1e-5)
# explore halo/block-cyclic extensions of the gather space (reference
# config.py `extend_space`)
extend_space = _env_bool("EASYDIST_EXTEND_SPACE", True)
# cap tensor elements during discovery: ops larger than this get hint-shrunk
# (reference torch/sharding_interpreter.py:256-313)
discovery_hint_numel = _env_int("EASYDIST_DISCOVERY_HINT_NUMEL", 2**24)
# hard cap on candidate shardings executed per shard group (the DFS is
# exponential in the number of tensor args; jax primitives rarely exceed 3)
discovery_max_candidates = _env_int("EASYDIST_DISCOVERY_MAX_CANDIDATES", 4096)

# ---------------- pruned discovery (jaxfront/discovery.py) ----------------
# Automap-style propagation grouping (arXiv:2112.02958): canonicalize eqn
# signatures into dim-role equivalence classes and run discovery once per
# group representative, instantiating the rule for every member.  The kill
# switch (EASYDIST_DISCOVERY_PRUNE=0) restores per-signature discovery
# end-to-end; chosen strategies are identical either way (gated by
# tests/test_jaxfront/test_discovery.py and bench.py --discovery).
discovery_prune = _env_bool("EASYDIST_DISCOVERY_PRUNE", True)
# persist discovered rules across process restarts, keyed by canonical
# signature + a knob/cost-model salt (atomic tempfile+replace store like
# the strategy cache's) — warm runs skip probe compilation entirely
discovery_persistent_cache = _env_bool("EASYDIST_DISCOVERY_CACHE", True)
# cache directory; empty = "<compile_cache_dir>/discovery"
discovery_cache_dir = os.environ.get("EASYDIST_DISCOVERY_CACHE_DIR", "")
# fuse a candidate's per-shard probe executions into ONE batched (vmapped)
# bind instead of nshards sequential eager calls; falls back to the
# sequential loop per-op on any batching failure
discovery_batch_probes = _env_bool("EASYDIST_DISCOVERY_BATCH_PROBES", True)
# analytic preset rules (jaxfront/presets.py); 0 forces execution
# discovery for every primitive (bench probe-ratio measurement uses this
# to compare pruned vs unpruned discovery on honest probe counts)
discovery_use_presets = _env_bool("EASYDIST_DISCOVERY_PRESETS", True)
# one-shot cross-check mode: execute-validate each analytic preset rule
# against the ShardCombine harness on small shapes (every preset shard
# group must execute and recombine exactly as declared); expensive,
# default off — enabled by the preset-validation test
discovery_crosscheck = _env_bool("EASYDIST_DISCOVERY_CROSSCHECK", False)

# ---------------- solver ----------------
enable_graph_coarsen = _env_bool("EASYDIST_ENABLE_GRAPH_COARSEN", True)
coarsen_level = _env_int("EASYDIST_COARSEN_LEVEL", 1)
solver_time_limit = _env_float("EASYDIST_SOLVER_TIME_LIMIT", 60.0)
solver_mip_rel_gap = _env_float("EASYDIST_SOLVER_MIP_REL_GAP", 1e-3)
all_to_all_punish_factor = _env_float("EASYDIST_ALL_TO_ALL_PUNISH", 3.0)
# allow re-picking a strategy already chosen on a previous mesh axis
allow_repeated_axis_strategy = _env_bool("EASYDIST_ALLOW_REPEATED_AXIS_STRATEGY", False)
# discount resharding cost when independent compute can hide the collective
# (reference predict_comm_overlap + comm_overlap_ratio, solver.py:74-84);
# the discount is bounded by the hideable seconds of independent peer work
# (MXU ops at peak_flops, memory-bound ops at hbm_bandwidth) per edge.
# The ratio the solver applies is resolved by
# autoflow.cost_model.overlap_discount_ratio() from three sources
# (`comm_overlap_ratio_source`):
#   "auto"     (default) the MEASURED fraction when runtime.calibrate.
#              calibrate_overlap() has recorded one for this backend in the
#              PerfDB (loaded at compile time by apply_calibration), else
#              the configured `comm_overlap_ratio`;
#   "measured" only the measured fraction — the discount is OFF (ratio 0)
#              until a calibration exists, so an uncalibrated compile can
#              never trade bytes for imagined overlap;
#   "config"   always the configured `comm_overlap_ratio` (the reference's
#              flat-guess behavior).
# predict_comm_overlap stays off by default: with the UNCALIBRATED flat 0.5
# guess, the GPT dp x tp solve picks plans moving ~1.5x the collective
# bytes of the byte-minimal plan (fails the hand-GSPMD quality gate); with
# a measured fraction the discount reflects what the runtime's
# backward-ordered bucket flush (comm/overlap.py) actually hides, and the
# same solve stays byte-minimal (tests/test_autoflow/
# test_overlap_pricing.py).  Calibrate once on the target, then enable.
predict_comm_overlap = _env_bool("EASYDIST_PREDICT_COMM_OVERLAP", False)
comm_overlap_ratio = _env_float("EASYDIST_COMM_OVERLAP_RATIO", 0.5)
comm_overlap_ratio_source = os.environ.get("EASYDIST_COMM_OVERLAP_SOURCE", "auto")
# set by runtime.calibrate (calibrate_overlap / apply_calibration), never
# by hand: achieved overlap fraction measured on THIS backend, or None
comm_overlap_ratio_measured = None
# device peak FLOP/s for overlap bounding (v5e bf16 ~197e12; f32 ~49e12);
# auto-replaced with the real device kind's datasheet value at compile time
# (runtime.calibrate.apply_device_constants) unless the env var is set
peak_flops = _env_float("EASYDIST_PEAK_FLOPS", 4.9e13)
# (mem_cost_weight was removed: the solver derives the memory tie-break
# weight from the comm-cost scale so it can order comm-equal solutions but
# never flip a comm decision — a fixed weight could do either)
# per-device memory cap in bytes: -1 = auto (ask the real device's
# memory_stats at compile; unknown backends stay uncapped), 0 = off,
# >0 = explicit cap.  v5e has 16 GiB HBM.
per_device_memory_cap = _env_int("EASYDIST_MEMORY_CAP", -1)
memory_ratio = _env_float("EASYDIST_MEMORY_RATIO", 0.9)
# compiler-chosen rematerialization when the planned peak exceeds the cap
# (schedule/remat.py); max eqns re-executed per recompute chain
enable_auto_remat = _env_bool("EASYDIST_AUTO_REMAT", True)
remat_max_chain_len = _env_int("EASYDIST_REMAT_MAX_CHAIN", 96)
liveness_only_input = _env_bool("EASYDIST_LIVENESS_ONLY_INPUT", False)
solver_backend = os.environ.get("EASYDIST_SOLVER", "milp")  # milp | beam
beam_width = _env_int("EASYDIST_BEAM_WIDTH", 100)
# tie ILP variables of isomorphic clusters (identical transformer layers
# collapse to one set of decision variables; solve time for an L-layer stack
# approaches the 1-layer solve)
solver_cluster_dedup = _env_bool("EASYDIST_SOLVER_CLUSTER_DEDUP", True)
# carry PARTIAL placements in the GLOBAL strategy pools so the ILP can
# defer an all-reduce across linear consumers (reference metair.py:376-481
# carries partials globally; previously composite-rule inner solves only)
enable_partial_pools = _env_bool("EASYDIST_PARTIAL_POOLS", True)
# lax.scan composite discovery: cap on per-seed body ILP solves (each seed
# dim of each scan operand costs one small ILP; real models have dozens)
scan_max_seed_solves = _env_int("EASYDIST_SCAN_MAX_SEED_SOLVES", 48)
# lax.while_loop trip count is unknown at trace time; this estimate scales
# the per-iteration collective price of a sharded loop body (solver only —
# a wrong guess shifts the shard/replicate crossover, never correctness)
while_trip_estimate = _env_int("EASYDIST_WHILE_TRIP_ESTIMATE", 16)
# warn when more than this fraction of modeled FLOPs lands on equations
# whose chosen strategy is all-replicate on every mesh axis — the
# silent-zero-parallelism failure mode (a user gets 1-chip performance on
# an 8-chip mesh with no signal)
replicate_warn_threshold = _env_float("EASYDIST_REPLICATE_WARN_THRESHOLD", 0.5)

# ---------------- mesh / comm cost model ----------------
# per-axis link bandwidth in bytes/s used to weight collective cost between
# mesh axes; ICI (intra-slice) vs DCN (cross-slice).  v5e: 4x 400Gbps ICI
# links/chip ≈ 200 GB/s; DCN ≈ 25 GB/s per host.
ici_bandwidth = _env_float("EASYDIST_ICI_BANDWIDTH", 2.0e11)
dcn_bandwidth = _env_float("EASYDIST_DCN_BANDWIDTH", 2.5e10)
# alpha term: fixed seconds per collective launch (ring setup + sync); makes
# the solver stop scattering tiny tensors whose collectives are pure latency
ici_latency = _env_float("EASYDIST_ICI_LATENCY", 1.0e-6)
dcn_latency = _env_float("EASYDIST_DCN_LATENCY", 2.0e-5)
# HBM bandwidth (bytes/s): prices the compute-redundancy of replicated ops
# (elementwise ops are memory-bound; v5e ~ 810 GB/s)
hbm_bandwidth = _env_float("EASYDIST_HBM_BANDWIDTH", 8.1e11)

# ---------------- gradient-collective compression (easydist_tpu.comm) ----
# wire dtype for gradient reductions: "none" (exact fp32 path, the
# default — emitted programs stay bitwise-identical to pre-comm behavior)
# | "int8" (two-pass block-scaled, ~3.9x fewer wire bytes) | "bf16" (cast,
# 2x).  See docs/COMM.md for the scheme and accuracy guidance.
comm_quant_dtype = os.environ.get("EASYDIST_COMM_QUANT", "none")
# elements per scaling block for int8 (one f32 scale per block; larger
# blocks = less scale overhead, coarser dynamic range)
comm_quant_block = _env_int("EASYDIST_COMM_QUANT_BLOCK", 256)
# fuse leaf gradients into buckets of at most this many bytes before
# reducing (0 = one collective per leaf, the historical emission).  Fewer
# launches amortize the per-collective alpha and fill the ICI rings.
comm_bucket_bytes = _env_int("EASYDIST_COMM_BUCKET_BYTES", 0)
# per-tree opt-out: leaves whose key path matches this regex (case-
# insensitive) stay at exact fp32 — norm scales/biases are tiny but
# disproportionately sensitive to quantization noise
# (the `'b'` alternative catches dict-key paths like "[0]['b']" that
# jax.tree_util.keystr produces for the toy models' bias leaves)
comm_quant_skip = os.environ.get(
    "EASYDIST_COMM_QUANT_SKIP", r"bias|norm|\bln\b|scale|gamma|beta|'b'")
# leaves below this many elements are never quantized: block padding plus
# per-block scales would move MORE bytes than fp32, and tiny collectives
# are alpha-bound anyway (bucket them instead)
comm_quant_min_numel = _env_int("EASYDIST_COMM_QUANT_MIN_NUMEL", 2048)
# ---------------- overlapped gradient collectives (comm/overlap.py) -------
# flush gradient buckets in backward EMISSION order, each launch pinned to
# the previous with optimization_barrier so XLA's latency-hiding scheduler
# slides the collective under the remaining backward compute.  Off by
# default: the dp/zero wrappers then emit the historical sequential flush
# (bitwise-identical programs).  Value-safe when on: reductions are
# elementwise, so the reordered flush is bitwise-identical to the
# sequential one whenever quantization is off (docs/COMM.md).
comm_overlap = _env_bool("EASYDIST_COMM_OVERLAP", False)
# K-microbatch double-buffered gradient accumulation in the dp/zero step
# builders: a lax.scan whose carry holds microbatch k-1's in-flight grads,
# reduced while microbatch k's backward runs.  0/1 = off (single-shot
# step); per-call kwargs on ddp_step/zero2_step/zero3_step override.
grad_accum_microbatches = _env_int("EASYDIST_GRAD_ACCUM_MICROBATCHES", 0)
# replace peak_flops/hbm_bandwidth defaults with the real device kind's
# datasheet constants at compile time (unknown backends keep the defaults)
auto_device_constants = _env_bool("EASYDIST_AUTO_DEVICE_CONSTANTS", True)
# load measured alpha/beta/HBM values from the PerfDB when present
# (runtime.calibrate.calibrate() records them on the target hardware)
auto_calibration = _env_bool("EASYDIST_AUTO_CALIBRATION", True)
multihost = _env_bool("EASYDIST_MULTIHOST", False)

# ---------------- static analyzer (easydist_tpu.analyze) ----------------
# run the layer-1 strategy verifier + solver objective audit after every
# per-axis solve, and the bucketer's plan self-check (both are pure python
# over already-built structures; cost is negligible next to the solve)
enable_analyze = _env_bool("EASYDIST_ANALYZE", True)
# error-severity findings raise AnalysisError; set 0 to demote to logging
# (the escape hatch for shipping past a false positive while it is triaged)
analyze_raise = _env_bool("EASYDIST_ANALYZE_RAISE", True)
# MEM004 HBM budget gate (bytes/device): -1 = auto (ask the real device's
# memory_stats; unknown backends fall back to hbm_capacity_default), 0 =
# gate off, >0 = explicit budget.  Unlike per_device_memory_cap (which
# DRIVES remat), this only verifies — it never changes the program.
analyze_hbm_budget = _env_int("EASYDIST_ANALYZE_HBM_BUDGET", -1)
# platform HBM capacity assumed when no real device answers (v5e: 16 GiB)
hbm_capacity_default = _env_int("EASYDIST_HBM_CAPACITY", 16 * 2**30)
# SCHED003: warn when a pipeline tick schedule's static bubble fraction
# (idle fwd/bwd slots over total slots) exceeds this
analyze_bubble_warn_frac = _env_float("EASYDIST_ANALYZE_BUBBLE_WARN", 0.6)

# ---------------- runtime ----------------
# donate params/opt-state buffers in the emitted jit (XLA buffer aliasing: the
# TPU analog of the reference's in-place CUDA memory reuse)
enable_donation = _env_bool("EASYDIST_ENABLE_DONATION", True)
# jax.remat policy applied to the emitted function: "none" | "dots" | "all"
remat_policy = os.environ.get("EASYDIST_REMAT_POLICY", "none")

# ---------------- decode serving (easydist_tpu.serve.generation) --------
# attention backend for the cache-carrying decode step: "auto" (Pallas
# single-query flash kernel on TPU, masked dot_general elsewhere), "flash"
# (force the kernel; interpreted off-TPU), "xla" (force the masked
# dot_general path), "paged" (force the page-gathering kernel in
# `paged_decode_attention`; contiguous callers degrade to auto).
# TRACE-AFFECTING: the backends emit different programs for identical
# input shapes, so this is part of the strategy-cache salt.
decode_attention_backend = os.environ.get("EASYDIST_DECODE_ATTENTION",
                                          "auto")
# K/V rows streamed per grid step by the decode kernel (VMEM residency per
# program is O(block), independent of cache length).  TRACE-AFFECTING:
# changes the pallas_call grid, so it salts the strategy cache too.
decode_block_k = _env_int("EASYDIST_DECODE_BLOCK_K", 256)
# attention backend for the chunked-prefill pass (`*_prefill_chunk`):
# "auto" | "xla" — both resolve to the masked dot_general path today; the
# knob reserves the dispatch point for a blocked Pallas prefill kernel.
# TRACE-AFFECTING: part of the strategy-cache salt like the decode backend.
prefill_attention_backend = os.environ.get("EASYDIST_PREFILL_ATTENTION",
                                           "auto")
# speculative decoding defaults (`ServeConfig.speculate_k` /
# `.speculate_drafter` read these when not set explicitly): k = draft
# tokens proposed per verify round (0 disables speculation entirely —
# the session never compiles a verify program), drafter = "ngram"
# (zero-cost prompt lookup) or "draft_model" (a second small model's
# cached greedy decode; the session needs a drafter/draft_model wired).
# NOT trace-affecting by themselves: the verify program's shape is
# (slots, k+1), which reaches the signature cache as an input shape.
speculate_k = _env_int("EASYDIST_SPECULATE_K", 0)
speculate_drafter = os.environ.get("EASYDIST_SPECULATE_DRAFTER", "ngram")

# ---------------- reshard (easydist_tpu.reshard) ----------------
# chunk ceiling (bytes) for redistribution plans: the "+ chunk" term of
# the RESHARD001 peak-live-bytes bound.  Each plan step stages at most
# this much on top of one src shard + one dst shard; smaller chunks cap
# transient memory at the price of more collective launches (the
# elastic.restore.oom recovery path halves this and re-plans).
reshard_chunk_bytes = _env_int("EASYDIST_RESHARD_CHUNK_BYTES", 64 * 2**20)

# ---------------- resilience (easydist_tpu.resilience) ----------------
# deterministic fault schedule, e.g. "step.nan_grad@7,ckpt.write.partial@2"
# — names must come from resilience.faultinject.FAULT_POINTS (validated at
# arm time AND at import time by the faultinject module); empty = disarmed
fault_plan = os.environ.get("EASYDIST_FAULT_PLAN", "")
# NaN/Inf step guard: lax.cond skip-and-hold folded into the compiled step
# (dp/zero builders + GuardedStep for the auto path).  Off by default —
# guard-off programs are bitwise-identical to pre-guard builds.
# TRACE-AFFECTING: part of the strategy-cache salt.
resilience_step_guard = _env_bool("EASYDIST_STEP_GUARD", False)
# consecutive non-finite steps the guard holds before raising
resilience_guard_max_skips = _env_int("EASYDIST_GUARD_MAX_SKIPS", 8)
# overflow scale decays by this factor on each held step ...
resilience_guard_scale_decay = _env_float("EASYDIST_GUARD_SCALE_DECAY", 0.5)
# ... and doubles back (capped at its initial value) after this many clean
# steps
resilience_guard_scale_growth_every = _env_int(
    "EASYDIST_GUARD_GROWTH_EVERY", 200)
# checkpoint save/load I/O retry policy: exponential backoff with jitter
resilience_ckpt_retries = _env_int("EASYDIST_CKPT_RETRIES", 3)
resilience_ckpt_backoff_s = _env_float("EASYDIST_CKPT_BACKOFF", 0.05)
resilience_ckpt_backoff_jitter = _env_float("EASYDIST_CKPT_JITTER", 0.25)
# SIGTERM grace budget: the final synchronous checkpoint must land inside
# this window (GCE preemptible gives 30s; TPU spot similar)
resilience_preempt_grace_s = _env_float("EASYDIST_PREEMPT_GRACE", 30.0)
# data-stall watchdog for the elastic loop: a batch fetch exceeding this
# raises DataStallError (0 = watchdog off)
resilience_data_timeout_s = _env_float("EASYDIST_DATA_TIMEOUT", 0.0)


def _validate_resilience() -> None:
    """Fail at import on out-of-range resilience knobs: a bad env var must
    not surface as a wedged recovery path mid-incident."""
    if resilience_guard_max_skips < 1:
        raise ValueError(
            f"EASYDIST_GUARD_MAX_SKIPS must be >= 1, got "
            f"{resilience_guard_max_skips}")
    if not 0.0 < resilience_guard_scale_decay <= 1.0:
        raise ValueError(
            f"EASYDIST_GUARD_SCALE_DECAY must be in (0, 1], got "
            f"{resilience_guard_scale_decay}")
    if resilience_guard_scale_growth_every < 1:
        raise ValueError(
            f"EASYDIST_GUARD_GROWTH_EVERY must be >= 1, got "
            f"{resilience_guard_scale_growth_every}")
    if resilience_ckpt_retries < 0:
        raise ValueError(
            f"EASYDIST_CKPT_RETRIES must be >= 0, got "
            f"{resilience_ckpt_retries}")
    if resilience_ckpt_backoff_s < 0:
        raise ValueError(
            f"EASYDIST_CKPT_BACKOFF must be >= 0, got "
            f"{resilience_ckpt_backoff_s}")
    if not 0.0 <= resilience_ckpt_backoff_jitter <= 1.0:
        raise ValueError(
            f"EASYDIST_CKPT_JITTER must be in [0, 1], got "
            f"{resilience_ckpt_backoff_jitter}")
    if resilience_preempt_grace_s <= 0:
        raise ValueError(
            f"EASYDIST_PREEMPT_GRACE must be > 0, got "
            f"{resilience_preempt_grace_s}")
    if resilience_data_timeout_s < 0:
        raise ValueError(
            f"EASYDIST_DATA_TIMEOUT must be >= 0, got "
            f"{resilience_data_timeout_s}")


_validate_resilience()

# ---------------- profiling / perf db ----------------
prof_db_path = os.environ.get("EASYDIST_PERF_DB", os.path.expanduser("~/.easydist_tpu/perf.db"))
enable_runtime_prof = _env_bool("EASYDIST_RUNTIME_PROF", False)
# price solver compute-redundancy with measured per-op seconds from the
# PerfDB when available (runtime/op_profile.py); proxy otherwise
use_op_cost_db = _env_bool("EASYDIST_OP_COST_DB", True)
