"""Host-side planning for portable array redistribution
(arXiv:2112.01075, PAPERS.md): given a tensor living as (mesh, spec) at
the source and wanted as (mesh, spec) at the destination, emit a
composed program of CHUNKED collective steps — slice / all-gather /
all-to-all / dynamic-update compositions — whose peak live bytes are
bounded by O(max(src_shard, dst_shard) + chunk), never the global array.

Everything in this module is pure numpy/python on *descriptions*: a
`MeshDesc` is serializable and survives the mesh it describes (the whole
point — an elastic restore plans src->dst where the SRC mesh no longer
exists, reading its description from the checkpoint manifest's mesh
fingerprint).  Execution lives in `reshard.exec`; checkpoint restore
planning in `reshard.restore`; pricing goes through the same
`autoflow/cost_model` alpha-beta collective forms the solver uses, so
the solver and the elastic path reason about redistribution with one
vocabulary (DistIR's deterministic-pricing principle, arXiv:2111.05426).

The RESHARD001 analyze rule audits every plan against `chunked_bound()`:
a plan whose `peak_live_bytes()` exceeds the bound silently degenerated
to global materialization — exactly the replicated-restore OOM hazard
this library exists to remove.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

FINGERPRINT_FORMAT = 1

# spec entry per tensor dim: an axis name (sharded along it) or None
Spec = Tuple[Optional[str], ...]
# half-open index window, one (start, stop) per tensor dim
Window = Tuple[Tuple[int, int], ...]


@dataclass(frozen=True)
class MeshDesc:
    """A device mesh as data: axis names/sizes plus the device kinds it
    was built over.  Serializable (`to_meta`/`from_meta`) so a checkpoint
    manifest can carry the SAVE-time mesh and restore can plan against it
    after the physical mesh is gone."""

    axis_names: Tuple[str, ...]
    axis_sizes: Tuple[int, ...]
    device_kinds: Tuple[str, ...] = ()

    def __post_init__(self):
        if len(self.axis_names) != len(self.axis_sizes):
            raise ValueError(
                f"axis_names {self.axis_names} and axis_sizes "
                f"{self.axis_sizes} differ in length")
        if any(s < 1 for s in self.axis_sizes):
            raise ValueError(f"axis sizes must be >= 1: {self.axis_sizes}")

    @property
    def n_devices(self) -> int:
        return int(np.prod(self.axis_sizes)) if self.axis_sizes else 1

    def axis_size(self, name: str) -> int:
        return self.axis_sizes[self.axis_names.index(name)]

    def to_meta(self) -> Dict[str, Any]:
        return {"axes": list(self.axis_names),
                "sizes": [int(s) for s in self.axis_sizes],
                "device_kinds": list(self.device_kinds)}

    @classmethod
    def from_meta(cls, meta: Dict[str, Any]) -> "MeshDesc":
        return cls(tuple(meta.get("axes", [])),
                   tuple(int(s) for s in meta.get("sizes", [])),
                   tuple(meta.get("device_kinds", [])))

    @classmethod
    def from_mesh(cls, mesh) -> "MeshDesc":
        """From a live jax Mesh."""
        kinds = tuple(sorted({getattr(d, "device_kind", "?")
                              for d in mesh.devices.flat}))
        return cls(tuple(mesh.axis_names),
                   tuple(int(s) for s in mesh.devices.shape), kinds)


# the destination of a host gather (export paths): one "device", the host
HOST = MeshDesc(("host",), (1,), ("host",))


def normalize_spec(spec: Sequence, ndim: int) -> Spec:
    """PartitionSpec-ish -> canonical per-dim tuple of axis-name-or-None,
    padded to `ndim`.  A multi-axis dim entry (tuple of names) is only
    supported for length 1; longer entries degrade that dim to
    replicated — the planner never guesses at block-cyclic layouts."""
    out: List[Optional[str]] = []
    for entry in tuple(spec)[:ndim]:
        if entry is None:
            out.append(None)
        elif isinstance(entry, str):
            out.append(entry)
        elif isinstance(entry, (tuple, list)):
            out.append(entry[0] if len(entry) == 1 else None)
        else:
            out.append(None)
    out.extend([None] * (ndim - len(out)))
    return tuple(out)


def _dim_block(dim: int, parts: int) -> int:
    return -(-dim // parts)  # ceil: jax pads the last shard on uneven dims


def device_windows(shape: Sequence[int], mesh: MeshDesc,
                   spec: Sequence) -> List[Window]:
    """Per-device global index windows, in row-major device order over the
    mesh axes (the order `Mesh(devices.reshape(sizes))` enumerates).
    Devices along mesh axes a spec does not use hold replicas (identical
    windows)."""
    shape = tuple(int(s) for s in shape)
    spec = normalize_spec(spec, len(shape))
    for name in spec:
        if name is not None and name not in mesh.axis_names:
            raise ValueError(
                f"spec axis {name!r} not in mesh axes {mesh.axis_names}")
    windows: List[Window] = []
    sizes = mesh.axis_sizes or (1,)
    for linear in range(mesh.n_devices):
        coords = np.unravel_index(linear, sizes) if mesh.axis_sizes else (0,)
        win: List[Tuple[int, int]] = []
        for d, dim in enumerate(shape):
            name = spec[d]
            if name is None:
                win.append((0, dim))
                continue
            k = mesh.axis_names.index(name)
            parts = mesh.axis_sizes[k]
            block = _dim_block(dim, parts)
            i = int(coords[k])
            win.append((min(i * block, dim), min((i + 1) * block, dim)))
        windows.append(tuple(win))
    return windows


def window_bytes(win: Window, itemsize: int) -> int:
    n = itemsize
    for lo, hi in win:
        n *= max(0, hi - lo)
    return n


def max_shard_bytes(shape: Sequence[int], itemsize: int, mesh: MeshDesc,
                    spec: Sequence) -> int:
    wins = device_windows(shape, mesh, spec)
    return max((window_bytes(w, itemsize) for w in wins), default=0)


def intersect(a: Window, b: Window) -> Optional[Window]:
    out = []
    for (alo, ahi), (blo, bhi) in zip(a, b):
        lo, hi = max(alo, blo), min(ahi, bhi)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


# ------------------------------------------------------------- chunking
def chunk_spans(total: int, per_chunk: int) -> List[Tuple[int, int]]:
    """[0, total) as half-open spans of at most `per_chunk` (>=1)."""
    per_chunk = max(1, int(per_chunk))
    if total <= 0:
        return [(0, 0)] if total == 0 else []
    return [(lo, min(lo + per_chunk, total))
            for lo in range(0, total, per_chunk)]


def chunk_waves(sizes: Sequence[int], limit: Optional[int]
                ) -> List[Tuple[int, int]]:
    """Greedy prefix batching of work items into waves whose summed bytes
    stay under `limit` (an item alone may exceed it — indivisible).  The
    SAME planner bounds in-flight bytes for fleet hot-page drain
    migration that bounds chunk bytes for array redistribution; returns
    half-open index spans over `sizes`."""
    n = len(sizes)
    if not n:
        return []
    if not limit or limit <= 0:
        return [(0, n)]
    waves: List[Tuple[int, int]] = []
    lo, acc = 0, 0
    for i, s in enumerate(sizes):
        if i > lo and acc + s > limit:
            waves.append((lo, i))
            lo, acc = i, 0
        acc += int(s)
    waves.append((lo, n))
    return waves


# ------------------------------------------------------------- the plan
@dataclass(frozen=True)
class ChunkOp:
    """One step of the composed redistribution program: move the data in
    `window` (global index coordinates) from wherever the src layout
    holds it into the dst layout.  `kind` names the collective the step
    lowers to; `bytes` is the chunk payload, `wire_bytes` what actually
    crosses links (0 when every dst device already holds its piece)."""

    window: Window
    kind: str  # "local" | "slice" | "all_gather" | "all_to_all" | "gather_host"
    bytes: int
    wire_bytes: int


@dataclass
class ReshardPlan:
    """A chunked redistribution program plus the byte accounting the
    RESHARD001 audit and the cost model price."""

    shape: Tuple[int, ...]
    dtype: str
    src_mesh: MeshDesc
    src_spec: Spec
    dst_mesh: MeshDesc
    dst_spec: Spec
    chunks: List[ChunkOp] = field(default_factory=list)
    chunk_limit_bytes: int = 0   # the requested ceiling
    min_chunk_bytes: int = 0     # smallest indivisible unit (one dim-0 row)
    src_shard_bytes: int = 0
    dst_shard_bytes: int = 0

    def global_bytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64) *
                   np.dtype(self.dtype).itemsize) if self.shape \
            else np.dtype(self.dtype).itemsize

    def wire_bytes(self) -> int:
        return sum(op.wire_bytes for op in self.chunks)

    def max_chunk_bytes(self) -> int:
        return max((op.bytes for op in self.chunks), default=0)

    def peak_live_bytes(self) -> int:
        """Worst-case per-device live bytes while the program runs: the
        source shard is still alive, the destination shard is being
        built, and one chunk is in flight."""
        return (self.src_shard_bytes + self.dst_shard_bytes
                + self.max_chunk_bytes())

    def chunked_bound(self) -> int:
        """The O(max(src_shard, dst_shard) + chunk) contract RESHARD001
        enforces.  The chunk term is the ceiling the plan was ASKED for
        (or the smallest indivisible unit when a single row exceeds it)
        — a plan whose actual chunks blew past that has degenerated
        toward global materialization."""
        chunk_ceiling = max(self.chunk_limit_bytes, self.min_chunk_bytes)
        return (2 * max(self.src_shard_bytes, self.dst_shard_bytes)
                + chunk_ceiling)

    def cost_s(self, axis=None) -> float:
        """Alpha-beta seconds of the program, priced through the same
        autoflow/cost_model forms the solver uses for resharding edges."""
        from easydist_tpu.autoflow import cost_model

        if axis is None:
            axis = cost_model.MeshAxisSpec(
                "reshard", max(self.src_mesh.n_devices,
                               self.dst_mesh.n_devices, 1))
        return cost_model.redistribution_cost(
            float(self.wire_bytes()),
            sum(1 for op in self.chunks if op.wire_bytes > 0), axis)

    def summary(self) -> Dict[str, Any]:
        return {"shape": list(self.shape), "dtype": self.dtype,
                "src": {"mesh": self.src_mesh.to_meta(),
                        "spec": list(self.src_spec)},
                "dst": {"mesh": self.dst_mesh.to_meta(),
                        "spec": list(self.dst_spec)},
                "n_chunks": len(self.chunks),
                "kinds": sorted({op.kind for op in self.chunks}),
                "wire_bytes": int(self.wire_bytes()),
                "peak_live_bytes": int(self.peak_live_bytes()),
                "chunked_bound": int(self.chunked_bound())}


def _classify(src_mesh: MeshDesc, src_spec: Spec,
              dst_mesh: MeshDesc, dst_spec: Spec) -> str:
    """Which collective family the per-chunk step lowers to."""
    if dst_mesh is HOST or dst_mesh == HOST:
        return "gather_host"
    if (src_mesh, src_spec) == (dst_mesh, dst_spec):
        return "local"
    src_dims = {d for d, a in enumerate(src_spec) if a is not None}
    dst_dims = {d for d, a in enumerate(dst_spec) if a is not None}
    if not src_dims:
        return "slice"          # replicated source: every chunk is local
    if src_dims and dst_dims and src_dims != dst_dims:
        return "all_to_all"     # repartition across different dims
    if dst_dims == src_dims:
        src_parts = [src_mesh.axis_size(src_spec[d]) for d in sorted(src_dims)]
        dst_parts = [dst_mesh.axis_size(dst_spec[d]) for d in sorted(dst_dims)]
        if dst_parts == src_parts:
            return "slice"      # same partition, different device set
        return "all_gather" if max(dst_parts) < max(src_parts) \
            else "all_to_all"   # coarsen = subgroup gather; refine = split
    return "all_gather"         # sharded -> replicated


def plan_redistribute(shape: Sequence[int], dtype,
                      src: Tuple[MeshDesc, Sequence],
                      dst: Tuple[MeshDesc, Sequence],
                      chunk_bytes: Optional[int] = None) -> ReshardPlan:
    """Plan moving one `shape`/`dtype` tensor from layout `src` to layout
    `dst`, each a (MeshDesc, spec) pair.  Chunks tile dim 0 so that no
    step stages more than `chunk_bytes` (default
    `edconfig.reshard_chunk_bytes`); a single dim-0 row is the
    indivisible floor.  Wire bytes per chunk are computed exactly from
    the index windows: a dst device's piece is free when the same-index
    src device already holds it (elastic shrink/grow keeps surviving
    devices at their old linear index, so the overlap is real, not an
    accident)."""
    from easydist_tpu import config as edconfig

    if chunk_bytes is None:
        chunk_bytes = edconfig.reshard_chunk_bytes
    chunk_bytes = int(chunk_bytes)
    shape = tuple(int(s) for s in shape)
    dtype = np.dtype(dtype)
    src_mesh, src_spec_in = src
    dst_mesh, dst_spec_in = dst
    src_spec = normalize_spec(src_spec_in, len(shape))
    dst_spec = normalize_spec(dst_spec_in, len(shape))
    itemsize = dtype.itemsize

    src_wins = device_windows(shape, src_mesh, src_spec)
    dst_wins = device_windows(shape, dst_mesh, dst_spec)
    plan = ReshardPlan(
        shape=shape, dtype=dtype.name,
        src_mesh=src_mesh, src_spec=src_spec,
        dst_mesh=dst_mesh, dst_spec=dst_spec,
        chunk_limit_bytes=chunk_bytes,
        src_shard_bytes=max(window_bytes(w, itemsize) for w in src_wins),
        dst_shard_bytes=max(window_bytes(w, itemsize) for w in dst_wins))

    if not shape:  # scalar: one indivisible chunk
        row_bytes = itemsize
        spans = [(0, 1)]
        full: Window = ()
    else:
        row_bytes = itemsize * int(
            np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 \
            else itemsize
        rows = max(1, chunk_bytes // max(row_bytes, 1))
        spans = chunk_spans(shape[0], rows)
        full = tuple((0, d) for d in shape[1:])
    plan.min_chunk_bytes = row_bytes

    kind = _classify(src_mesh, src_spec, dst_mesh, dst_spec)
    for lo, hi in spans:
        win: Window = ((lo, hi),) + full if shape else ()
        payload = window_bytes(win, itemsize) if shape else itemsize
        wire = 0
        if kind != "local":
            for j, dwin in enumerate(dst_wins):
                need = intersect(dwin, win) if shape else win
                if shape and need is None:
                    continue
                need_b = window_bytes(need, itemsize) if shape else itemsize
                local_b = 0
                if j < len(src_wins):
                    have = intersect(src_wins[j], need) if shape else need
                    if not shape or have is not None:
                        local_b = window_bytes(have, itemsize) if shape \
                            else itemsize
                wire += max(0, need_b - local_b)
        plan.chunks.append(ChunkOp(window=win, kind=kind,
                                   bytes=payload, wire_bytes=wire))
    return plan


# --------------------------------------------------- mesh fingerprinting
def sharding_desc(sharding, ndim: int) -> Tuple[Optional[MeshDesc], Spec]:
    """(MeshDesc, spec) of a live jax sharding; (None, replicated) for
    single-device / unknown shardings."""
    spec_tuple = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if mesh is None or spec_tuple is None:
        return None, normalize_spec((), ndim)
    try:
        return (MeshDesc.from_mesh(mesh),
                normalize_spec(tuple(spec_tuple), ndim))
    except Exception:
        return None, normalize_spec((), ndim)


def state_fingerprint(state: Any) -> Dict[str, Any]:
    """The mesh fingerprint `save_checkpoint` stamps into the manifest
    meta: current device population (count + kinds) plus, per array
    leaf in flatten order, its shape/dtype and SAVE-time (mesh, spec).
    Restore compares this against the live topology to detect a shift
    and to plan the per-leaf src->dst redistribution."""
    import jax

    devices = jax.devices()
    leaves_meta: List[Dict[str, Any]] = []
    leaves, _treedef = jax.tree_util.tree_flatten(state)
    for leaf in leaves:
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            leaves_meta.append({"kind": "opaque"})
            continue
        entry: Dict[str, Any] = {
            "kind": "array",
            "shape": [int(s) for s in leaf.shape],
            "dtype": str(np.dtype(leaf.dtype)),
        }
        mesh_desc, spec = sharding_desc(getattr(leaf, "sharding", None),
                                        len(leaf.shape))
        if mesh_desc is not None and mesh_desc.n_devices > 1:
            entry["mesh"] = mesh_desc.to_meta()
            entry["spec"] = [s for s in spec]
        leaves_meta.append(entry)
    return {"format": FINGERPRINT_FORMAT,
            "n_devices": len(devices),
            "device_kinds": sorted({getattr(d, "device_kind", "?")
                                    for d in devices}),
            "leaves": leaves_meta}


def topology_shifted(saved_fp: Optional[Dict[str, Any]],
                     devices=None) -> bool:
    """True when the saved fingerprint describes a different device
    population than the live one (count or kinds) — the signal that
    restore must plan redistribution instead of assuming layouts match."""
    if not saved_fp:
        return False
    import jax

    devices = jax.devices() if devices is None else devices
    kinds = sorted({getattr(d, "device_kind", "?") for d in devices})
    return (int(saved_fp.get("n_devices", -1)) != len(devices)
            or list(saved_fp.get("device_kinds", [])) != kinds)
