"""Checkpoint-restore planning on the redistribution substrate.

`plan_restore(like, saved_meta)` turns the manifest's mesh fingerprint
(what the state looked like at SAVE time) plus the restore template
(what the caller wants NOW) into per-leaf destination shardings and
`ReshardPlan`s:

  * template leaf already carries a multi-device sharding -> that IS the
    destination (the caller's jit owns the layout); the plan prices the
    saved->template move.
  * template leaf is host/single-device but the fingerprint recorded a
    (mesh, spec) for it -> re-fit the saved mesh onto the CURRENT device
    population (outermost axis scales by the device ratio) and keep the
    saved spec, so a shrunk/grown restart restores each leaf SHARDED —
    per-device bytes stay O(leaf/n_devices), never the replicated
    fallback.
  * no usable information -> replicated over current devices (the
    legacy fallback); the caller is told how many bytes that costs per
    device so it can warn against the HBM budget.

The orbax reader already fetches only each shard's byte ranges when
given sharded targets, so executing these plans is exactly "restore into
the planned shardings" — the plan is what makes the byte bound
auditable (RESHARD001) before any I/O happens.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import plan as planlib

logger = logging.getLogger(__name__)


@dataclass
class RestorePlan:
    """Per-leaf destinations + plans for one checkpoint restore."""

    topology_shift: bool = False
    had_fingerprint: bool = False
    # flat, aligned with jax.tree_util.tree_flatten(like):
    shardings: List[Any] = field(default_factory=list)
    plans: List[Tuple[int, planlib.ReshardPlan]] = field(
        default_factory=list)
    # (leaf index, per-device bytes) of leaves falling back to replicated
    replicated_leaves: List[Tuple[int, int]] = field(default_factory=list)

    def peak_live_bytes(self) -> int:
        return max((p.peak_live_bytes() for _i, p in self.plans), default=0)

    def chunked_bound(self) -> int:
        return max((p.chunked_bound() for _i, p in self.plans), default=0)

    def replicated_bytes_per_device(self) -> int:
        return sum(b for _i, b in self.replicated_leaves)

    def summary(self) -> Dict[str, Any]:
        return {"topology_shift": self.topology_shift,
                "had_fingerprint": self.had_fingerprint,
                "n_planned": len(self.plans),
                "n_replicated": len(self.replicated_leaves),
                "replicated_bytes_per_device":
                    int(self.replicated_bytes_per_device()),
                "peak_live_bytes": int(self.peak_live_bytes()),
                "chunked_bound": int(self.chunked_bound())}


def _fit_mesh(saved: planlib.MeshDesc, n_now: int
              ) -> Optional[planlib.MeshDesc]:
    """Re-fit a saved mesh onto `n_now` devices: the OUTERMOST axis
    absorbs the device ratio (elastic scale events add/remove whole
    slices along one axis); None when no integer fit exists."""
    p = saved.n_devices
    if p == n_now:
        return saved
    sizes = list(saved.axis_sizes)
    if not sizes:
        return None
    scaled = sizes[0] * n_now
    if scaled % p != 0:
        return None
    new0 = scaled // p
    if new0 < 1:
        return None
    return planlib.MeshDesc(saved.axis_names, (new0, *sizes[1:]),
                            saved.device_kinds)


def _build_mesh(desc: planlib.MeshDesc):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:desc.n_devices]).reshape(
        desc.axis_sizes)
    return Mesh(devs, desc.axis_names)


def _replicated_sharding():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.array(jax.devices())
    return NamedSharding(Mesh(devs, ("restore",)), PartitionSpec())


def plan_restore(like: Any, saved_meta: Optional[Dict[str, Any]],
                 chunk_bytes: Optional[int] = None) -> RestorePlan:
    """Build the restore plan for template `like` given the checkpoint
    manifest's `mesh` fingerprint (None for legacy checkpoints)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    fp = (saved_meta or {}).get("mesh") if saved_meta else None
    # process-level shift (device population/kind changed) is necessary
    # but not sufficient: a restart onto a SUB-mesh of the same process
    # (the in-process drill, or a job shrinking within one slice) shows
    # the same jax.devices() — the per-leaf saved->destination mesh
    # comparison below catches those too
    out = RestorePlan(had_fingerprint=bool(fp),
                      topology_shift=planlib.topology_shifted(fp))
    leaves, _treedef = jax.tree_util.tree_flatten(like)
    saved_leaves = list(fp.get("leaves", [])) if fp else []
    n_now = len(jax.devices())
    rep = None
    mesh_cache: Dict[planlib.MeshDesc, Any] = {}

    for i, leaf in enumerate(leaves):
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            out.shardings.append(None)
            continue
        shape = tuple(int(s) for s in leaf.shape)
        itemsize = np.dtype(leaf.dtype).itemsize
        leaf_bytes = int(np.prod(shape, dtype=np.int64)) * itemsize \
            if shape else itemsize

        saved = saved_leaves[i] if i < len(saved_leaves) else {}
        src_desc = None
        if (saved.get("kind") == "array" and "mesh" in saved
                and list(saved.get("shape", [])) == list(shape)):
            src_desc = (planlib.MeshDesc.from_meta(saved["mesh"]),
                        planlib.normalize_spec(
                            tuple(saved.get("spec", [])), len(shape)))

        template_sharding = getattr(leaf, "sharding", None)
        if (template_sharding is not None
                and getattr(template_sharding, "num_devices", 1) > 1):
            # the caller's layout wins; the plan prices saved -> template
            out.shardings.append(template_sharding)
            dst_desc = planlib.sharding_desc(template_sharding, len(shape))
            if dst_desc[0] is not None and src_desc is not None:
                if (dst_desc[0].n_devices != src_desc[0].n_devices
                        or dst_desc[0].axis_sizes != src_desc[0].axis_sizes):
                    out.topology_shift = True
                out.plans.append((i, planlib.plan_redistribute(
                    shape, leaf.dtype, src_desc, dst_desc,
                    chunk_bytes=chunk_bytes)))
            continue

        if src_desc is not None:
            fitted = _fit_mesh(src_desc[0], n_now)
            spec = src_desc[1]
            if fitted is not None and any(a is not None for a in spec):
                if fitted not in mesh_cache:
                    mesh_cache[fitted] = _build_mesh(fitted)
                sharding = NamedSharding(mesh_cache[fitted],
                                         PartitionSpec(*spec))
                if fitted != src_desc[0]:
                    out.topology_shift = True
                out.shardings.append(sharding)
                out.plans.append((i, planlib.plan_redistribute(
                    shape, leaf.dtype, src_desc, (fitted, spec),
                    chunk_bytes=chunk_bytes)))
                continue

        # legacy fallback: replicated over the current devices —
        # per-device cost is the WHOLE leaf, which is what the caller's
        # HBM-budget warning is about
        if rep is None:
            rep = _replicated_sharding()
        out.shardings.append(rep)
        out.replicated_leaves.append((i, leaf_bytes))
    return out
