"""Portable chunked array redistribution (arXiv:2112.01075).

One substrate for every reshard path in the system: elastic checkpoint
restore onto a shrunk/grown mesh (`reshard.restore` + runtime/
checkpoint.py), pp `export_state_dict` host gathers (`fetch_chunked`),
fleet hot-page drain migration (`chunk_waves` via fleet/transport.py),
and live-array moves between arbitrary (mesh, spec) pairs
(`redistribute`).  Plans are priced through `autoflow/cost_model` and
audited by the analyze layer (RESHARD001/RESHARD002) against the
O(max(src_shard, dst_shard) + chunk) peak-live-bytes contract.
"""

from .plan import (  # noqa: F401
    HOST,
    ChunkOp,
    MeshDesc,
    ReshardPlan,
    chunk_spans,
    chunk_waves,
    device_windows,
    normalize_spec,
    plan_redistribute,
    sharding_desc,
    state_fingerprint,
    topology_shifted,
)
from .exec import (  # noqa: F401
    ReshardOOMError,
    fetch_chunked,
    redistribute,
)
from .restore import RestorePlan, plan_restore  # noqa: F401

__all__ = [
    "HOST", "ChunkOp", "MeshDesc", "ReshardPlan", "RestorePlan",
    "ReshardOOMError", "chunk_spans", "chunk_waves", "device_windows",
    "fetch_chunked", "normalize_spec", "plan_redistribute",
    "plan_restore", "redistribute", "sharding_desc", "state_fingerprint",
    "topology_shifted",
]
