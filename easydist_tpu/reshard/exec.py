"""Execute a `ReshardPlan` on live arrays.

Two lowering strategies, picked by what the device sets allow:

  * **collective path** — src and dst shardings enumerate the SAME
    device list: each ChunkOp becomes the jit program its `kind` names
    (dynamic_slice replicated over the dst mesh = slice + all-gather of
    ONE chunk; dynamic_update_slice into the donated dst buffer lands
    it).  Per-device live bytes are src_shard + dst_shard + chunk —
    exactly `plan.peak_live_bytes()`.

  * **staged path** — device sets differ (elastic shrink/grow, where
    half the source mesh is gone or the target has fresh devices): each
    dst shard is assembled on host from the source's addressable shards
    in chunk-bounded copies and `device_put` one shard at a time, then
    stitched with `make_array_from_single_device_arrays`.  Host live
    bytes are one dst shard + one chunk; the global array never exists
    anywhere.

`fetch_chunked` is the export-path variant (device -> host numpy) the pp
`export_state_dict` re-packing rides: per-shard chunked reads instead of
one global `device_get`.

Every entry point audits its plan through the analyze layer
(RESHARD001: peak live bytes must stay under the chunked bound) before
moving a byte.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from . import plan as planlib

logger = logging.getLogger(__name__)


class ReshardOOMError(RuntimeError):
    """A chunked transfer step exceeded its memory budget (or the
    `elastic.restore.oom` fault point said it did); recoverable by
    re-planning with a smaller chunk."""


def _audit(rplan: planlib.ReshardPlan, node: str) -> None:
    try:
        from easydist_tpu.analyze import check_reshard_plan
    except ImportError:  # analyze is an optional layer at runtime
        return
    check_reshard_plan(rplan, node=node)


def _desc_of(sharding, ndim: int):
    mesh_desc, spec = planlib.sharding_desc(sharding, ndim)
    if mesh_desc is None:
        mesh_desc = planlib.MeshDesc(("rep",), (1,))
    return mesh_desc, spec


def _device_list(sharding):
    import jax

    mesh = getattr(sharding, "mesh", None)
    if mesh is not None:
        return list(mesh.devices.flat)
    try:
        return list(sharding._device_assignment)
    except Exception:
        return list(jax.devices())


def _norm_windows(indices_map, shape):
    """devices_indices_map slices -> {device: Window} with concrete
    bounds."""
    out = {}
    for dev, idx in indices_map.items():
        win = []
        for sl, dim in zip(idx, shape):
            lo, hi, _ = sl.indices(dim)
            win.append((lo, hi))
        out[dev] = tuple(win)
    return out


def redistribute(x, dst_sharding, *, chunk_bytes: Optional[int] = None,
                 rplan: Optional[planlib.ReshardPlan] = None,
                 node: str = "redistribute"):
    """Move `x` to `dst_sharding` as a composed chunked program planned
    by `plan_redistribute` (or the caller-supplied `rplan`).  Returns an
    array committed to exactly `dst_sharding`; never materializes the
    global array on any device."""
    import jax

    src_sharding = getattr(x, "sharding", None)
    if src_sharding is not None and dst_sharding is not None:
        eq = getattr(src_sharding, "is_equivalent_to", None)
        try:
            if eq is not None and eq(dst_sharding, x.ndim):
                return x  # already there: the zero-cost fast path
        except Exception:
            pass
    if rplan is None:
        src_desc = _desc_of(src_sharding, x.ndim)
        dst_desc = _desc_of(dst_sharding, x.ndim)
        rplan = planlib.plan_redistribute(
            x.shape, x.dtype, src_desc, dst_desc, chunk_bytes=chunk_bytes)
    _audit(rplan, node)

    src_devs = _device_list(src_sharding) if src_sharding is not None else []
    dst_devs = _device_list(dst_sharding)
    if src_devs == dst_devs and len(dst_devs) > 0:
        return _exec_collective(x, dst_sharding, rplan)
    return _exec_staged(x, dst_sharding, rplan)


def _exec_collective(x, dst_sharding, rplan: planlib.ReshardPlan):
    """Same-device-set lowering: per chunk, a replicated dynamic_slice
    (GSPMD emits slice + all-gather of just the chunk) then a donated
    dynamic_update_slice into the dst-sharded output buffer."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = dst_sharding.mesh
    rep = NamedSharding(mesh, PartitionSpec())
    shape, dtype = x.shape, x.dtype

    out = jax.jit(lambda: jnp.zeros(shape, dtype),
                  out_shardings=dst_sharding)()

    # two static chunk geometries at most (uniform spans + a ragged
    # tail), so the jit cache stays warm across the loop
    def slice_fn(a, starts, sizes):
        return lax.dynamic_slice(a, starts, sizes)

    slice_jit = jax.jit(slice_fn, static_argnames=("sizes",),
                        out_shardings=rep)
    update_jit = jax.jit(
        lambda o, c, starts: lax.dynamic_update_slice(o, c, starts),
        out_shardings=dst_sharding, donate_argnums=0)

    for op in rplan.chunks:
        starts = tuple(jnp.asarray(lo, jnp.int32) for lo, _hi in op.window)
        sizes = tuple(hi - lo for lo, hi in op.window)
        if not starts:  # scalar
            return jax.device_put(x, dst_sharding)
        chunk = slice_jit(x, starts, sizes)
        out = update_jit(out, chunk, starts)
    return out


def _exec_staged(x, dst_sharding, rplan: planlib.ReshardPlan):
    """Cross-device-set lowering: build each dst shard on host from the
    src's addressable shards, one shard and one chunk-bounded copy at a
    time, then stitch the sharded array without a global buffer."""
    import jax

    shape = tuple(x.shape)
    dtype = np.dtype(x.dtype)
    src_shards = [(tuple((sl.indices(d)[0], sl.indices(d)[1])
                         for sl, d in zip(s.index, shape)),
                   s.data) for s in x.addressable_shards]
    dst_map = _norm_windows(
        dst_sharding.devices_indices_map(shape), shape)

    bufs = []
    for dev, dwin in dst_map.items():
        buf = np.empty([hi - lo for lo, hi in dwin], dtype)
        for op in rplan.chunks:
            region = planlib.intersect(dwin, op.window) if shape else dwin
            if shape and region is None:
                continue
            for swin, sdata in src_shards:
                ov = planlib.intersect(swin, region) if shape else swin
                if shape and ov is None:
                    continue
                # replicas overwrite with identical values — harmless
                dst_idx = tuple(slice(lo - dlo, hi - dlo) for (lo, hi),
                                (dlo, _dhi) in zip(ov, dwin))
                src_idx = tuple(slice(lo - slo, hi - slo) for (lo, hi),
                                (slo, _shi) in zip(ov, swin))
                buf[dst_idx] = np.asarray(sdata)[src_idx]
        bufs.append(jax.device_put(buf, dev))
    return jax.make_array_from_single_device_arrays(
        shape, dst_sharding, bufs)


def fetch_chunked(x, chunk_bytes: Optional[int] = None,
                  node: str = "fetch") -> np.ndarray:
    """Device -> host gather in chunk-bounded per-shard reads (the
    export-path replacement for a global `jax.device_get`).  The full
    host buffer is the POINT of an export; what the plan bounds is the
    staging: no read moves more than one chunk, no device ever holds
    more than its shard."""
    src_sharding = getattr(x, "sharding", None)
    src_desc = _desc_of(src_sharding, getattr(x, "ndim", 0))
    rplan = planlib.plan_redistribute(
        x.shape, x.dtype, src_desc, (planlib.HOST, ()),
        chunk_bytes=chunk_bytes)
    _audit(rplan, node)

    shape = tuple(x.shape)
    dtype = np.dtype(x.dtype)
    out = np.empty(shape, dtype)
    if not shape:
        return np.asarray(x)
    shards = getattr(x, "addressable_shards", None)
    if not shards:
        return np.asarray(x)
    seen = set()
    for s in shards:
        swin = tuple((sl.indices(d)[0], sl.indices(d)[1])
                     for sl, d in zip(s.index, shape))
        if swin in seen:
            continue  # replica: identical bytes, skip the re-copy
        seen.add(swin)
        data = None
        for op in rplan.chunks:
            ov = planlib.intersect(swin, op.window)
            if ov is None:
                continue
            if data is None:
                data = np.asarray(s.data)  # one shard staged at a time
            dst_idx = tuple(slice(lo, hi) for lo, hi in ov)
            src_idx = tuple(slice(lo - slo, hi - slo) for (lo, hi),
                            (slo, _shi) in zip(ov, swin))
            out[dst_idx] = data[src_idx]
    return out
