"""SIGTERM preemption handling for the training loop.

TPU VMs (and every preemptible/spot pool) deliver SIGTERM with a grace
window before the kill.  The default Python behavior — raise KeyboardInterrupt
nowhere, die mid-checkpoint — is exactly the partial-write failure the
checkpoint commit protocol exists to survive; but surviving is worse than
not crashing: the handler here converts the signal into a FLAG, the elastic
loop checks it at the next step boundary, takes one synchronous final
checkpoint inside the grace budget, and exits through a typed
`PreemptedError` that carries the step it persisted.

Signal handlers only install from the main thread; elsewhere (a training
loop driven from a worker thread) the handler degrades to flag-only mode
and `request()` remains available for the embedding process to call.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)


class PreemptedError(RuntimeError):
    """The loop exited because preemption was requested; `step` is the last
    step whose state was checkpointed before exit."""

    def __init__(self, step: int, checkpoint_s: float):
        self.step = step
        self.checkpoint_s = checkpoint_s
        super().__init__(
            f"preempted: final checkpoint at step {step} took "
            f"{checkpoint_s:.2f}s; exiting for restart-resume")


class PreemptionHandler:
    """Context manager: arms a SIGTERM-to-flag handler for the loop body.

        with PreemptionHandler(grace_s=30.0) as pre:
            for step in ...:
                if pre.requested:
                    <final checkpoint>; raise PreemptedError(...)
    """

    def __init__(self, grace_s: float = 30.0):
        if grace_s <= 0:
            raise ValueError(f"grace_s must be > 0, got {grace_s}")
        self.grace_s = grace_s
        self._event = threading.Event()
        self._prev = None
        self._installed = False
        self._requested_t: Optional[float] = None

    # ------------------------------------------------------------ lifecycle
    def __enter__(self) -> "PreemptionHandler":
        try:
            self._prev = signal.signal(signal.SIGTERM, self._on_signal)
            self._installed = True
        except ValueError:
            # non-main thread: signals unavailable; request() still works
            logger.warning(
                "preempt: not on the main thread, SIGTERM handler not "
                "installed (flag-only mode)")
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev)
            self._installed = False

    # ------------------------------------------------------------ signaling
    def _on_signal(self, signum, frame) -> None:
        self.request()

    def request(self) -> None:
        """Mark preemption requested (signal handler or embedder call)."""
        if not self._event.is_set():
            self._requested_t = time.monotonic()
            logger.warning(
                "preempt: termination requested; final checkpoint at the "
                "next step boundary (grace budget %.1fs)", self.grace_s)
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def grace_remaining(self) -> float:
        """Seconds left of the grace budget (inf before any request)."""
        if self._requested_t is None:
            return float("inf")
        return self.grace_s - (time.monotonic() - self._requested_t)
