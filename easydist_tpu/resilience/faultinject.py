"""Deterministic fault-injection harness (DistIR principle, PAPERS.md
arXiv:2111.05426: distributed-execution behavior verified by deterministic
simulation instead of real hardware).

Every recovery path in the resilience layer is exercised by NAMED fault
points armed from a schedule string — the same code path a real failure
takes, reproduced on the single-core CPU CI mesh.  A fault point is a call
site like::

    if faultinject.fire("ckpt.manifest.corrupt"):
        <site-specific corruption>

or, for sites whose fault is simply "the process died here"::

    faultinject.crash_point("ckpt.write.partial")   # raises InjectedFault

Schedule syntax (``EASYDIST_FAULT_PLAN`` / ``arm()``):

    "step.nan_grad@7"                 fire on the 7th hit of that point
    "ckpt.write.partial@2,data.stall@1"   multiple points, comma-separated
    "serve.exec_timeout@*"            fire on EVERY hit
    "fleet.replica.crash@3,fleet.replica.crash@9"   fire on hits 3 AND 9

Counting is per-point and 1-based: ``name@N`` fires exactly once, when the
Nth execution of that fault point is reached; repeating a name schedules a
SET of occurrences (the chaos drill's kill schedule).  Disarmed (the
default), every fault point is a single attribute check + ``False`` — zero
overhead and no behavioral difference, which is what lets the instrumented
code paths stay in production builds.

The catalog below is closed: arming an unknown point name raises
immediately with a closest-match suggestion (a typo'd plan must not
silently test nothing).
"""

from __future__ import annotations

import difflib
import os
import threading
from typing import Dict, List, Optional, Tuple

# closed catalog: every instrumented fault point, with the recovery
# contract it exercises (docs/RESILIENCE.md keeps the long-form table)
FAULT_POINTS = frozenset({
    # checkpoint commit protocol (runtime/checkpoint.py)
    "ckpt.write.partial",     # crash mid-write: tempdir left, no commit
    "ckpt.manifest.corrupt",  # bit rot in a COMMITTED checkpoint's data
    # training loop (runtime/elastic.py + resilience/guard.py)
    "preempt.sigterm",        # host preemption signal at a step boundary
    "step.nan_grad",          # poisoned batch -> non-finite gradients
    "data.stall",             # input pipeline stops producing
    # elastic topology shift (runtime/elastic.py + runtime/checkpoint.py
    # + reshard/)
    "elastic.mesh.shrink",    # slice shrank: SIGTERM, restart on fewer devices
    "elastic.restore.chunk_corrupt",  # bit rot in the checkpoint being restored
    "elastic.restore.oom",    # chunked restore step exceeds its memory budget
    # serving (serve/engine.py)
    "serve.exec_timeout",     # executable dispatch exceeds the watchdog
    "serve.oom_bucket",       # batch-bucket compile exhausts device memory
    # fleet serving (fleet/router.py, fleet/transport.py, fleet/health.py)
    "fleet.replica.crash",    # replica process dies mid-decode
    "fleet.transport.stall",  # KV page transfer attempt hangs past budget
    "fleet.transport.page_corrupt",  # bit flip in a page in flight
    "fleet.probe.flap",       # health probe falsely reports no progress
    # SLO autoscaler (sim/autoscale.py)
    "autoscale.metrics.stale",   # planner sees frozen occupancy/p99
    "autoscale.scaleup.fail",    # replica spin-up raises mid-ramp
    # host KV tier (kv/tier.py)
    "kv.tier.fetch_corrupt",  # demotion fetch corrupt: manifest catch+refetch
    "kv.tier.host_oom",       # host allocation fails: hold-and-warn pause
})


class InjectedFault(RuntimeError):
    """Raised by `crash_point` sites: the deterministic stand-in for "the
    process died here".  Deliberately a RuntimeError so generic
    `except Exception` recovery paths treat it like any real failure."""

    def __init__(self, point: str):
        self.point = point
        super().__init__(f"injected fault at {point!r}")


class FaultPlanError(ValueError):
    """The schedule string is malformed or names an uncatalogued point."""


_lock = threading.Lock()
# None = disarmed (the zero-overhead fast path checks only this);
# else {point: occurrence int or "*"}
_plan: Optional[Dict[str, object]] = None
_hits: Dict[str, int] = {}
_fired: Dict[str, int] = {}


def parse_plan(spec: str) -> Dict[str, object]:
    """``"a@2,b@*"`` -> ``{"a": 2, "b": "*"}`` and ``"a@2,a@5"`` ->
    ``{"a": frozenset({2, 5})}``; raises
    FaultPlanError on unknown names / malformed entries, with a
    closest-match suggestion for typos.  Repeated entries for one point
    accumulate into a set of occurrences (a kill SCHEDULE); ``@*``
    anywhere for a point means every hit and absorbs numeric entries."""
    out: Dict[str, object] = {}
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        name, sep, occ = entry.partition("@")
        if not sep:
            raise FaultPlanError(
                f"fault plan entry {entry!r} missing '@occurrence' "
                f"(use 'name@N' or 'name@*')")
        if name not in FAULT_POINTS:
            close = difflib.get_close_matches(name, sorted(FAULT_POINTS),
                                              n=1, cutoff=0.4)
            hint = f" — did you mean {close[0]!r}?" if close else ""
            raise FaultPlanError(
                f"unknown fault point {name!r}{hint}; catalogued points: "
                f"{sorted(FAULT_POINTS)}")
        if occ == "*":
            out[name] = "*"
            continue
        try:
            n = int(occ)
        except ValueError:
            raise FaultPlanError(
                f"fault plan occurrence {occ!r} for {name!r} is not an "
                f"integer or '*'") from None
        if n < 1:
            raise FaultPlanError(
                f"fault occurrence must be >= 1 (1-based), got {n} "
                f"for {name!r}")
        prev = out.get(name)
        if prev == "*":
            continue  # every-hit already covers n
        if prev is None:
            out[name] = n
        else:
            prevs = {prev} if isinstance(prev, int) else set(prev)
            out[name] = frozenset(prevs | {n})
    return out


def arm(spec: str) -> None:
    """Arm the harness with a schedule string; empty string disarms."""
    global _plan
    plan = parse_plan(spec) if spec else None
    with _lock:
        _plan = plan or None
        _hits.clear()
        _fired.clear()


def disarm() -> None:
    arm("")


def armed() -> bool:
    return _plan is not None


def fire(point: str) -> bool:
    """Count a hit of `point`; True iff the armed schedule says this hit
    is the faulty one.  Disarmed: a single load + compare, no locking."""
    if _plan is None:  # fast path: production / faults-off CI
        return False
    if point not in FAULT_POINTS:
        raise FaultPlanError(f"uncatalogued fault point {point!r} in code")
    with _lock:
        if _plan is None:
            return False
        _hits[point] = _hits.get(point, 0) + 1
        occ = _plan.get(point)
        hit = (occ == "*" or _hits[point] == occ
               or (isinstance(occ, frozenset) and _hits[point] in occ))
        if hit:
            _fired[point] = _fired.get(point, 0) + 1
        return hit


def crash_point(point: str) -> None:
    """`fire` + raise: for sites whose injected fault is process death."""
    if fire(point):
        raise InjectedFault(point)


def stats() -> Dict[str, Dict[str, int]]:
    """{"hits": {...}, "fired": {...}} snapshot (bench/test reporting)."""
    with _lock:
        return {"hits": dict(_hits), "fired": dict(_fired)}


def unfired() -> List[Tuple[str, object]]:
    """Scheduled (point, occurrence) pairs the run never reached — a drill
    that "passed" without firing its faults tested nothing, so drills gate
    on this being empty.  ``@*`` entries count as unfired until the point
    fired at least once."""
    out: List[Tuple[str, object]] = []
    with _lock:
        if _plan is None:
            return out
        for point, occ in sorted(_plan.items()):
            hits = _hits.get(point, 0)
            if occ == "*":
                if _fired.get(point, 0) == 0:
                    out.append((point, "*"))
            elif isinstance(occ, frozenset):
                out.extend((point, n) for n in sorted(occ) if hits < n)
            elif hits < occ:  # single int occurrence
                out.append((point, occ))
    return out


def export_stats(db=None, key: str = "resilience",
                 sub_key: str = "fault_plan", persist: bool = False):
    """Append the armed plan + hit/fired/unfired counters to the PerfDB
    (the store serving metrics already land in), so a chaos drill's
    record proves every scheduled fault actually fired."""
    if db is None:
        from easydist_tpu.runtime.perfdb import PerfDB

        db = PerfDB()
    with _lock:
        plan = {p: (occ if occ == "*" else sorted(occ)
                    if isinstance(occ, frozenset) else occ)
                for p, occ in (_plan or {}).items()}
    db.append_history(key, sub_key, {
        "plan": plan, **stats(),
        "unfired": [[p, occ] for p, occ in unfired()]})
    if persist:
        try:
            db.persist()
        except Exception:  # stats export must never fail a drill
            pass
    return db


class fault_plan:
    """Context manager for tests: arm on enter, restore on exit.

        with faultinject.fault_plan("step.nan_grad@3"):
            run_training(...)
    """

    def __init__(self, spec: str):
        self.spec = spec
        self._saved: Optional[Dict[str, object]] = None

    def __enter__(self) -> "fault_plan":
        global _plan
        self._saved = _plan
        arm(self.spec)
        return self

    def __exit__(self, *exc) -> None:
        global _plan
        with _lock:
            _plan = self._saved
            _hits.clear()
            _fired.clear()


def arm_from_config() -> None:
    """Arm from `edconfig.fault_plan` (the EASYDIST_FAULT_PLAN schedule).
    Called by the entry points that own a process lifetime (elastic loop,
    bench scenarios) — library code never arms implicitly."""
    from easydist_tpu import config as edconfig

    spec = getattr(edconfig, "fault_plan", "") or ""
    if spec:
        arm(spec)


# arming at import time would make library import order matter; instead the
# env plan is validated eagerly (a typo'd plan fails fast, before any run)
_env_spec = os.environ.get("EASYDIST_FAULT_PLAN", "")
if _env_spec:
    parse_plan(_env_spec)
