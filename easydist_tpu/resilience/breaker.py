"""Circuit breaker for the serving engine.

When the executor starts failing persistently (device wedged, tunnel down,
every batch timing out), retrying each request individually multiplies the
damage: every queued request burns a full watchdog timeout before failing,
latency explodes, and the queue stays pinned at capacity.  The breaker
converts persistent failure into FAST, structured rejection at the door —
clients see `CircuitOpenError` with a retry-after hint instead of a
timeout, and the engine probes recovery on its own schedule.

Classic three-state machine, clock-injectable for deterministic tests:

    CLOSED     normal admission; failures/latency tracked
    OPEN       everything shed until `cooldown_s` elapses
    HALF_OPEN  a limited number of probe requests admitted; one success
               closes the circuit, one failure re-opens it

Trip conditions (either):
  * `failure_threshold` consecutive executor failures, or
  * observed p99 execute latency above `p99_threshold_s` once at least
    `min_samples` executions were seen (the brownout trip: the device is
    answering, but so slowly that admitting more load only digs deeper).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Thread-safe; all transitions under one lock (serving hot path does
    one lock acquisition per admit/record — negligible next to dispatch)."""

    def __init__(self, failure_threshold: int = 5,
                 cooldown_s: float = 1.0,
                 p99_threshold_s: Optional[float] = None,
                 min_samples: int = 20,
                 half_open_probes: int = 1,
                 p99: Optional[Callable[[], Optional[float]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 replica_id: Optional[str] = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        if half_open_probes < 1:
            raise ValueError(
                f"half_open_probes must be >= 1, got {half_open_probes}")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.p99_threshold_s = p99_threshold_s
        self.min_samples = min_samples
        self.half_open_probes = half_open_probes
        self._p99 = p99  # callable returning current p99 seconds (or None)
        self.clock = clock
        # fleet label: stamped into snapshot() so per-replica breaker
        # states aggregate without key collisions
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._samples = 0
        self._opened_t: Optional[float] = None
        self._probes_in_flight = 0
        self._times_opened = 0

    # -------------------------------------------------------------- queries
    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._state == OPEN and self._opened_t is not None \
                and self.clock() - self._opened_t >= self.cooldown_s:
            self._state = HALF_OPEN
            self._probes_in_flight = 0
        return self._state

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN or self._opened_t is None:
                return 0.0
            return max(0.0, self.cooldown_s
                       - (self.clock() - self._opened_t))

    def snapshot(self) -> dict:
        with self._lock:
            return {"replica_id": self.replica_id,
                    "state": self._state_locked(),
                    "consecutive_failures": self._consecutive_failures,
                    "times_opened": self._times_opened}

    # ---------------------------------------------------------- transitions
    def allow(self) -> bool:
        """Admission decision.  CLOSED -> True; OPEN -> False; HALF_OPEN ->
        True for up to `half_open_probes` in-flight probes."""
        with self._lock:
            state = self._state_locked()
            if state == CLOSED:
                return True
            if state == OPEN:
                return False
            if self._probes_in_flight >= self.half_open_probes:
                return False
            self._probes_in_flight += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            self._samples += 1
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probes_in_flight = 0
            # brownout trip: healthy completions but pathological latency
            if self._state == CLOSED and self.p99_threshold_s is not None \
                    and self._p99 is not None \
                    and self._samples >= self.min_samples:
                p99 = self._p99()
                if p99 is not None and p99 > self.p99_threshold_s:
                    self._trip_locked()

    def record_failure(self) -> None:
        with self._lock:
            self._samples += 1
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._trip_locked()
            elif self._state == CLOSED \
                    and self._consecutive_failures >= self.failure_threshold:
                self._trip_locked()

    def _trip_locked(self) -> None:
        self._state = OPEN
        self._opened_t = self.clock()
        self._probes_in_flight = 0
        self._times_opened += 1
