"""`easydist_tpu.resilience`: fault-injection-first robustness layer.

The reference has no recovery story at all (SURVEY §5: "Failure detection /
elastic recovery — Absent") and delegates failure to torchrun killing
peers.  This package turns recovery into a TESTED CONTRACT, DistIR-style
(PAPERS.md arXiv:2111.05426): every failure mode is a named, deterministic
fault point (`faultinject`) that CPU CI arms on a schedule, so each
recovery path below runs as an ordinary test:

  faultinject   named fault points + `EASYDIST_FAULT_PLAN` schedules;
                zero-overhead no-ops when disarmed
  guard         NaN/Inf step guard: lax.cond skip-and-hold inside the
                compiled step, overflow-scale decay, bounded skip budget
  preempt       SIGTERM -> flag -> final checkpoint within a grace budget
  breaker       serving circuit breaker (consecutive-failure / p99 trips)

The hardened checkpoint commit protocol lives with the checkpoint code
(`runtime/checkpoint.py`), the guarded loop in `runtime/elastic.py`, the
serving degradation in `serve/engine.py`; this package holds the shared
machinery.  Catalog + recovery semantics: docs/RESILIENCE.md.
"""

from . import faultinject  # noqa: F401
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker  # noqa: F401
from .faultinject import (FAULT_POINTS, FaultPlanError,  # noqa: F401
                          InjectedFault, fault_plan)
from .guard import (GuardBudgetExceededError, GuardedStep,  # noqa: F401
                    all_finite, guard_train_step, init_guard_state,
                    poison_batch)
from .preempt import PreemptedError, PreemptionHandler  # noqa: F401

__all__ = [
    "faultinject", "FAULT_POINTS", "FaultPlanError", "InjectedFault",
    "fault_plan",
    "GuardBudgetExceededError", "GuardedStep", "all_finite",
    "guard_train_step", "init_guard_state", "poison_batch",
    "PreemptedError", "PreemptionHandler",
    "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
]
