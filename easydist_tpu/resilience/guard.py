"""NaN/Inf step guard: skip-and-hold folded into the compiled train step.

A single poisoned batch (or an overflowed bf16 reduction) otherwise writes
NaN into every parameter and the run is dead from that step on — the
classic silent-loss-of-progress failure.  The guard computes the candidate
step normally, checks every inexact leaf of the candidate state (and the
loss) for finiteness, and `lax.cond`-selects between candidate and previous
state: a non-finite step HOLDS the previous parameters instead of
committing garbage.

Guard state (a small pytree threaded through the step, so the whole thing
lives inside one jit):

    consecutive   int32  non-finite steps in a row (reset on a clean step)
    skips         int32  total held steps
    steps         int32  total steps seen
    scale         f32    overflow scale: decays on each skip, recovers
                         after `scale_growth_every` clean steps — exposed
                         for callers that fold it into their loss as a
                         dynamic loss scale

Budget enforcement is host-side (`GuardedStep`): tracing cannot raise, so
the wrapper reads the consecutive-skip counter after each step and raises
`GuardBudgetExceededError` once it exceeds the bounded budget — a step
function that produces NaN every time must kill the job loudly, not spin
holding stale state forever.

With the guard OFF nothing here is traced at all — the dp/zero builders and
`run_training` bypass this module entirely, so guard-off programs stay
bitwise-identical to pre-guard builds (tested by jaxpr identity in
tests/test_resilience/test_guard.py).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from . import faultinject


class GuardBudgetExceededError(RuntimeError):
    """More consecutive non-finite steps than the guard budget allows."""

    def __init__(self, consecutive: int, budget: int):
        self.consecutive = consecutive
        self.budget = budget
        super().__init__(
            f"step guard held {consecutive} consecutive non-finite steps "
            f"(budget {budget}); the step function is producing NaN/Inf "
            f"every step — aborting instead of silently spinning")


def init_guard_state(scale: float = 1.0):
    """Fresh guard-state pytree (goes into the guarded step's carry)."""
    return {"consecutive": jnp.zeros((), jnp.int32),
            "skips": jnp.zeros((), jnp.int32),
            "steps": jnp.zeros((), jnp.int32),
            "scale": jnp.asarray(scale, jnp.float32)}


def all_finite(*trees):
    """Traced scalar bool: every inexact leaf of every tree is finite.
    Non-float leaves (step counters, int tables) are exempt — integer
    arithmetic cannot produce NaN and wraps silently either way."""
    flags = []
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(jnp.result_type(leaf), jnp.inexact):
                flags.append(jnp.all(jnp.isfinite(leaf)))
    if not flags:
        return jnp.bool_(True)
    return functools.reduce(jnp.logical_and, flags)


def guard_train_step(step_fn: Callable, *,
                     scale_decay: Optional[float] = None,
                     scale_growth_every: Optional[int] = None,
                     scale_max: float = 1.0) -> Callable:
    """Wrap `step_fn(state, *batch) -> (state, loss)` into
    `gstep((state, guard_state), *batch) -> ((state, guard_state), loss)`
    with the skip-and-hold fold.  Pure and traceable: compose under
    jax.jit / shard_map freely.

    The returned loss is the CANDIDATE loss untouched (NaN on a skipped
    step) — hiding it would blind host-side monitoring to the overflow the
    guard just absorbed.
    """
    from easydist_tpu import config as edconfig

    decay = (edconfig.resilience_guard_scale_decay if scale_decay is None
             else scale_decay)
    growth_every = (edconfig.resilience_guard_scale_growth_every
                    if scale_growth_every is None else scale_growth_every)
    if not 0.0 < decay <= 1.0:
        raise ValueError(f"scale_decay must be in (0, 1], got {decay}")
    if growth_every < 1:
        raise ValueError(
            f"scale_growth_every must be >= 1, got {growth_every}")

    def gstep(carry, *batch):
        state, gs = carry
        cand_state, loss = step_fn(state, *batch)
        finite = all_finite(cand_state, loss)
        # lax.cond skip-and-hold: commit the candidate only when every
        # inexact leaf survived; both operands exist either way, so the
        # cond is the SELECT, not a recomputation
        new_state = jax.lax.cond(finite,
                                 lambda c, p: c, lambda c, p: p,
                                 cand_state, state)
        consecutive = jnp.where(finite, 0, gs["consecutive"] + 1)
        clean_run = jnp.where(finite, gs["steps"] - gs["skips"] + 1, 0)
        grown = jnp.where(
            finite & (clean_run % growth_every == 0),
            jnp.minimum(gs["scale"] * 2.0, scale_max), gs["scale"])
        new_gs = {
            "consecutive": consecutive.astype(jnp.int32),
            "skips": gs["skips"] + jnp.where(finite, 0, 1).astype(jnp.int32),
            "steps": gs["steps"] + 1,
            "scale": jnp.where(finite, grown, gs["scale"] * decay),
        }
        return (new_state, new_gs), loss

    return gstep


def poison_batch(batch):
    """Replace the first inexact array arg with NaN of the same
    shape/dtype (the `step.nan_grad` fault action: a poisoned input is the
    deterministic stand-in for an overflowed gradient).  Falls back to an
    integer arg (poisoned with an out-of-range sentinel is NOT safe — int
    lookups would just gather garbage), so an all-int batch poisons the
    LOSS path by scaling instead; callers with all-int batches should
    inject at the loss."""
    import numpy as np

    out = list(batch)
    for i, a in enumerate(out):
        if hasattr(a, "dtype") and jnp.issubdtype(
                jnp.result_type(a), jnp.inexact):
            out[i] = jnp.full(jnp.shape(a), jnp.nan, jnp.result_type(a))
            return tuple(out)
    raise ValueError(
        "step.nan_grad needs at least one float batch argument to poison; "
        "this batch has none (int token batches: inject at the loss "
        "instead)")


class GuardedStep:
    """Host wrapper owning the guard state and the skip budget.

    Works over ANY `step_fn(state, *batch) -> (state, loss)` — a dp/zero
    builder's jitted step, an `easydist_compile` CompiledFunction (the auto
    path), or a plain function.  The guard arithmetic runs as traced jax
    ops; only the budget check reads one scalar back per step.

        guarded = GuardedStep(step)
        for batch in data:
            state, loss = guarded(state, *batch)
    """

    def __init__(self, step_fn: Callable,
                 max_consecutive_skips: Optional[int] = None, *,
                 scale_decay: Optional[float] = None,
                 scale_growth_every: Optional[int] = None,
                 init_scale: float = 1.0):
        from easydist_tpu import config as edconfig

        self.budget = (edconfig.resilience_guard_max_skips
                       if max_consecutive_skips is None
                       else max_consecutive_skips)
        if self.budget < 1:
            raise ValueError(
                f"max_consecutive_skips must be >= 1, got {self.budget}")
        self._gstep = guard_train_step(
            step_fn, scale_decay=scale_decay,
            scale_growth_every=scale_growth_every, scale_max=init_scale)
        self.guard_state = init_guard_state(init_scale)

    def __call__(self, state, *batch):
        if faultinject.fire("step.nan_grad"):
            batch = poison_batch(batch)
        (state, self.guard_state), loss = self._gstep(
            (state, self.guard_state), *batch)
        consecutive = int(self.guard_state["consecutive"])
        if consecutive > self.budget:
            raise GuardBudgetExceededError(consecutive, self.budget)
        return state, loss

    def stats(self) -> dict:
        return {k: (float(v) if k == "scale" else int(v))
                for k, v in self.guard_state.items()}
