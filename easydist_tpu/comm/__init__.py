"""Quantized, bucketed gradient collectives (see docs/COMM.md).

Layers:
  quant.py     block-scaled int8 / bf16 reduction collectives with an exact
               fp32 fallback (bitwise-identical when disabled)
  bucketer.py  fuse many small leaf collectives into few fixed-size buckets
  reduce.py    the grad-path entry points (DDP tree reduce, ZeRO leaf
               reduce_scatter, partial-region fences)
  overlap.py   backward-ordered, barrier-pinned bucket flush and
               double-buffered K-microbatch gradient accumulation
  counters.py  trace-time bytes/launch accounting, exported via PerfDB
"""

from .bucketer import Bucket, bucketed_reduce, pack, plan_buckets, unpack  # noqa: F401
from .overlap import (accumulate_gradients, chain_leaf_reduces,  # noqa: F401
                      grad_emission_order, overlapped_reduce_gradients,
                      schedulable_overlap_fraction)
from .counters import (CommCounters, comm_counters,  # noqa: F401
                       ring_all_gather_bytes, ring_all_reduce_bytes,
                       ring_reduce_scatter_bytes)
from .quant import (bf16_psum, bf16_psum_scatter, comm_enabled,  # noqa: F401
                    dequantize_blockwise, int8_payload_bytes,
                    leaf_quantizable, quant_mode, quantize_blockwise,
                    quantized_psum, quantized_psum_scatter)
from .reduce import (all_reduce_grad, fence_psum,  # noqa: F401
                     fence_psum_scatter, reduce_gradients,
                     reduce_scatter_grad)
