"""Trace-time communication accounting for the gradient-collective layer.

Every collective the `easydist_tpu.comm` wrappers emit is recorded HERE at
trace time (the wrappers run while jax traces the step, so shapes/dtypes
are static and the byte math is exact).  Wire bytes use the same ring
closed forms as `autoflow/cost_model.py` so the counters and the solver
agree on what a collective costs:

  all_reduce       2 * payload * (n-1)/n      (reduce-scatter + all-gather)
  reduce_scatter   payload * (n-1)/n
  all_gather       payload * (n-1)/n

`bytes_fp32_equiv` is what the SAME reductions would have moved at full
precision without bucketing — the denominator of the compression ratio the
bench and dryrun report.  Counters export through the runtime PerfDB under
the ``comm_stats`` key so perf evidence persists next to step times.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


def ring_all_reduce_bytes(payload_bytes: float, n: int) -> float:
    return 2.0 * payload_bytes * (n - 1) / n if n > 1 else 0.0


def ring_reduce_scatter_bytes(payload_bytes: float, n: int) -> float:
    return payload_bytes * (n - 1) / n if n > 1 else 0.0


def ring_all_gather_bytes(payload_bytes: float, n: int) -> float:
    return payload_bytes * (n - 1) / n if n > 1 else 0.0


class CommCounters:
    """Accumulates per-trace collective launches and bytes; thread-safe
    (ServeEngine compiles buckets concurrently)."""

    _FIELDS = ("launches", "quantized_launches", "fallback_launches",
               "bytes_on_wire", "bytes_fp32_equiv", "bucketed_leaves")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.launches = 0
            self.quantized_launches = 0
            self.fallback_launches = 0
            self.bytes_on_wire = 0.0
            self.bytes_fp32_equiv = 0.0
            self.bucketed_leaves = 0

    def record(self, *, launches: int = 1, bytes_on_wire: float = 0.0,
               bytes_fp32_equiv: float = 0.0, quantized: bool = False,
               fallback: bool = False, bucketed_leaves: int = 0) -> None:
        with self._lock:
            self.launches += launches
            if quantized:
                self.quantized_launches += launches
            if fallback:
                self.fallback_launches += launches
            self.bytes_on_wire += bytes_on_wire
            self.bytes_fp32_equiv += bytes_fp32_equiv
            self.bucketed_leaves += bucketed_leaves

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            snap = {k: getattr(self, k) for k in self._FIELDS}
        wire, full = snap["bytes_on_wire"], snap["bytes_fp32_equiv"]
        snap["compression_ratio"] = (wire / full) if full > 0 else 1.0
        return snap

    def export_to_perfdb(self, sub_key: str = "comm",
                         db: Optional[object] = None) -> Dict[str, float]:
        """Persist the current snapshot under ("comm_stats", sub_key) so the
        bench/dryrun byte evidence lands next to the step-time history."""
        from easydist_tpu.runtime.perfdb import PerfDB

        snap = self.snapshot()
        db = db or PerfDB()
        db.record_op_perf("comm_stats", sub_key, snap)
        try:
            db.persist()
        except Exception:  # a read-only DB path must not break the trace
            pass
        return snap


# module-global instance the wrappers record into (mirrors how edconfig is
# one flat module: one process, one accounting stream; reset() per scenario)
comm_counters = CommCounters()
