"""Gradient bucketing: fuse many small per-leaf collectives into few large
fixed-size ones.

Every DP/ZeRO grad path historically launched one collective per leaf
tensor; a transformer has hundreds of sub-MB leaves, so the sync step pays
hundreds of alpha (launch latency) terms and never reaches peak ICI
utilization.  The bucketer partitions the leaves into buckets of at most
``comm_bucket_bytes`` (grouped by dtype and by quantizability so packing
is cast-free and the opt-out leaves never share a quantized wire), packs
each bucket into one 1-D vector, reduces it with ONE collective, and
unpacks the results back into the original tree — the TPU analog of the
reference's fused NCCL gradient buckets.

Packing/unpacking is pure data movement (`ravel`/`concatenate`/`split`/
`reshape`); the reduction itself is elementwise, so a bucketed fp32 psum
is value-identical to the per-leaf psums it replaces.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Sequence

import jax.numpy as jnp

from easydist_tpu import config as edconfig


@dataclass
class Bucket:
    """One fused collective: which flat-leaf indices ride it."""
    indices: List[int] = field(default_factory=list)
    nbytes: int = 0
    quantize: bool = False
    dtype: object = None


def plan_buckets(leaves: Sequence, bucket_bytes: int,
                 quantize_flags: Sequence[bool]) -> List[Bucket]:
    """Greedy fixed-size packing in leaf order, grouped by
    (dtype, quantize).  ``bucket_bytes <= 0`` means no fusion: every leaf
    gets its own bucket (quantization may still apply)."""
    buckets: List[Bucket] = []
    open_by_group = {}
    for i, (leaf, qz) in enumerate(zip(leaves, quantize_flags)):
        nbytes = leaf.size * leaf.dtype.itemsize
        group = (jnp.dtype(leaf.dtype), bool(qz))
        cur = open_by_group.get(group)
        if (bucket_bytes <= 0 or cur is None
                or (cur.nbytes + nbytes > bucket_bytes and cur.indices)):
            cur = Bucket(quantize=bool(qz), dtype=group[0])
            buckets.append(cur)
            if bucket_bytes > 0:
                open_by_group[group] = cur
        cur.indices.append(i)
        cur.nbytes += nbytes
    return buckets


def pack(leaves: Sequence, bucket: Bucket):
    """Concatenate the bucket's raveled leaves into one 1-D vector."""
    if len(bucket.indices) == 1:
        return leaves[bucket.indices[0]].reshape(-1)
    return jnp.concatenate([leaves[i].reshape(-1) for i in bucket.indices])


def unpack(flat, bucket: Bucket, leaves: Sequence) -> dict:
    """Split a reduced bucket vector back into {leaf_index: leaf} with the
    original shapes."""
    out = {}
    offset = 0
    for i in bucket.indices:
        n = leaves[i].size
        out[i] = flat[offset:offset + n].reshape(leaves[i].shape)
        offset += n
    return out


def bucketed_reduce(leaves: Sequence, quantize_flags: Sequence[bool],
                    bucket_bytes: int,
                    reduce_fn: Callable) -> List:
    """Reduce `leaves` bucket-by-bucket.

    ``reduce_fn(flat_1d, bucket) -> reduced_flat_1d`` performs the actual
    collective (quantized or not, per ``bucket.quantize``).  Returns the
    reduced leaves in the original flat order.
    """
    buckets = plan_buckets(leaves, bucket_bytes, quantize_flags)
    if edconfig.enable_analyze:
        # trace-time self-check (COLL003): a plan whose slices do not tile
        # the flat buffer silently corrupts gradients at unpack; cost is
        # O(leaves) python at trace time
        from easydist_tpu.analyze import check_bucket_plan

        check_bucket_plan(leaves, buckets)
    reduced: List = [None] * len(leaves)
    for b in buckets:
        flat = pack(leaves, b)
        out = reduce_fn(flat, b)
        for i, leaf in unpack(out, b, leaves).items():
            reduced[i] = leaf
    return reduced
