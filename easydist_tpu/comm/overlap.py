"""Overlapped gradient collectives: backward-ordered bucket flush and
double-buffered gradient accumulation.

PR 2's `reduce_gradients` packs leaves into buckets in FLAT TREE order and
reduces them all after backward finishes, so on a real mesh every wire
second is exposed.  This module closes that gap in two ways:

  * `overlapped_reduce_gradients` plans buckets over the gradients'
    EMISSION order in the backward jaxpr (last layer's grads come first)
    and launches the per-bucket collectives as a chain pinned with
    `jax.lax.optimization_barrier`.  The chain serializes the collectives
    against each other — matching the one-channel reality of a ring — but
    leaves them data-independent from the *rest* of the program, so XLA's
    latency-hiding scheduler can slide each reduce under whatever backward
    compute is still outstanding.  Values are bitwise-identical to the
    sequential flush when quantization is off (pmean/psum are elementwise;
    pack/unpack and the barrier are bit-preserving), which is what the
    OVL parity gates in tests/ and `__graft_entry__` assert.

  * `accumulate_gradients` builds a K-microbatch step where microbatch
    k's backward overlaps the reduction of microbatch k-1's gradients: a
    `lax.scan` whose carry holds the in-flight (not yet reduced) gradient
    tree.  The fold order of the accumulator is identical between the
    overlapped and sequential variants, so the two are bitwise-equal with
    quantization off.  `parallel/dp.py` exposes this as the opt-in
    ``grad_accum_microbatches`` knob on ddp/zero2/zero3.

The achieved overlap is *measured* by `runtime.calibrate.calibrate_overlap`
and fed back into the solver through `autoflow.cost_model.
overlap_discount_ratio` — see docs/COMM.md ("Overlapped flush").
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from easydist_tpu import config as edconfig

from .bucketer import pack, plan_buckets, unpack
from .quant import leaf_quantizable, quant_mode

logger = logging.getLogger(__name__)

__all__ = [
    "grad_emission_order",
    "overlapped_reduce_gradients",
    "chain_leaf_reduces",
    "accumulate_gradients",
]


# ----------------------------------------------------------- emission order

def _call_jaxpr(eqn):
    """The sub-jaxpr a call-like eqn (pjit/closed_call/remat/custom_vjp)
    delegates to, or None for plain primitives."""
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        return getattr(sub, "jaxpr", sub)  # ClosedJaxpr -> core jaxpr
    return None


def _emission_keys(jaxpr, outvars, prefix=()):
    """Per-outvar sort key: the (possibly nested) index of the producing
    equation.  Vars produced by the same call-like eqn are disambiguated
    by recursing into its sub-jaxpr; literals / free vars sort first."""
    produced = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for ov in eqn.outvars:
            if not hasattr(ov, "val"):  # skip literals
                produced[ov] = i
    keys: List[Optional[tuple]] = [None] * len(outvars)
    groups = {}
    for k, v in enumerate(outvars):
        if hasattr(v, "val") or v not in produced:
            keys[k] = prefix + (-1,)
        else:
            groups.setdefault(produced[v], []).append(k)
    for i, idxs in groups.items():
        eqn = jaxpr.eqns[i]
        sub = _call_jaxpr(eqn)
        if (sub is not None and len(idxs) > 1
                and len(sub.outvars) == len(eqn.outvars)):
            inner = []
            for k in idxs:
                pos = next(j for j, o in enumerate(eqn.outvars)
                           if o is outvars[k])
                inner.append(sub.outvars[pos])
            for k, key in zip(idxs, _emission_keys(sub, inner, prefix + (i,))):
                keys[k] = key
        else:
            for k in idxs:
                keys[k] = prefix + (i,)
    return keys


def grad_emission_order(loss_fn: Callable, params, *batch) -> List[int]:
    """Flat-leaf permutation of ``jax.grad(loss_fn)(params, *batch)``
    sorted by gradient EMISSION order in the backward jaxpr.

    The backward pass produces the LAST layer's gradients first, so for a
    >=2-layer model this is not the identity permutation — flushing
    buckets in this order lets the first collective launch while earlier
    layers' backward compute is still running.  Traced abstractly
    (ShapeDtypeStructs), no FLOPs spent; falls back to identity order if
    the trace fails (custom pytrees, data-dependent control flow).
    """
    flat, _ = jax.tree_util.tree_flatten(params)
    identity = list(range(len(flat)))
    try:
        abstract = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l), jnp.result_type(l)),
            (params, tuple(batch)))
        closed = jax.make_jaxpr(
            lambda p, b: jax.grad(loss_fn)(p, *b))(*abstract)
        keys = _emission_keys(closed.jaxpr, closed.jaxpr.outvars)
        order = sorted(identity, key=lambda k: (keys[k], k))
    except Exception as exc:  # pragma: no cover - defensive
        logger.warning("grad_emission_order: trace failed (%s); "
                       "falling back to flat tree order", exc)
        return identity
    if sorted(order) != identity:  # pragma: no cover - defensive
        logger.warning("grad_emission_order: non-permutation result; "
                       "falling back to flat tree order")
        return identity
    return order


def schedulable_overlap_fraction(loss_fn: Callable, params, *batch) -> float:
    """Byte-weighted share of the gradient flush's collective traffic that
    the backward-ordered chain launches while backward compute is still
    OUTSTANDING — the program-structure upper bound on what a
    latency-hiding backend can hide.

    Leaf i's gradient is emitted at top-level equation e_i of the E-eqn
    backward jaxpr, so when its reduce launches, a (E-1-e_i)/(E-1) share
    of the backward pass has not yet run; that share (clamped to [0, 1])
    is weighted by the leaf's wire bytes.  Deterministic — no timing, so
    single-core CI hosts (where wall-clock concurrency is physically
    zero) still gate on it; the MEASURED counterpart is
    `runtime.profiler.measure_collective_overlap`.  The reference's
    unordered post-backward flush scores exactly 0 by construction.
    Returns 0.0 when the backward trace fails.
    """
    flat, _ = jax.tree_util.tree_flatten(params)
    try:
        abstract = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l), jnp.result_type(l)),
            (params, tuple(batch)))
        closed = jax.make_jaxpr(
            lambda p, b: jax.grad(loss_fn)(p, *b))(*abstract)
        keys = _emission_keys(closed.jaxpr, closed.jaxpr.outvars)
        n_eqns = len(closed.jaxpr.eqns)
    except Exception as exc:
        logger.warning("schedulable_overlap_fraction: trace failed (%s)",
                       exc)
        return 0.0
    if n_eqns <= 1 or not flat or len(keys) != len(flat):
        return 0.0
    total = hideable = 0.0
    for leaf, key in zip(flat, keys):
        size = 1
        for d in jnp.shape(leaf):
            size *= d
        nbytes = float(size * jnp.dtype(jnp.result_type(leaf)).itemsize)
        remaining = (n_eqns - 1 - key[0]) / (n_eqns - 1)
        total += nbytes
        hideable += nbytes * min(max(remaining, 0.0), 1.0)
    return hideable / total if total else 0.0


def _valid_order(order, n: int) -> bool:
    try:
        return sorted(int(i) for i in order) == list(range(n))
    except (TypeError, ValueError):
        return False


def _maybe_check(leaves, order, buckets) -> None:
    if not edconfig.enable_analyze:
        return
    from easydist_tpu.analyze import check_overlap_plan

    check_overlap_plan(leaves, order, buckets)


# --------------------------------------------------------- overlapped flush

def overlapped_reduce_gradients(grads, axis_name: str, axis_size: int,
                                op: str = "pmean",
                                emission_order: Optional[Sequence[int]] = None,
                                pin_chain: bool = True):
    """Backward-ordered, barrier-pinned bucket flush of a gradient pytree.

    Buckets are planned over the leaves REORDERED by ``emission_order``
    (from `grad_emission_order`; identity when None), then reduced as a
    chain: bucket k's packed payload is fused with a one-element token of
    bucket k-1's result through `optimization_barrier`, which (a) keeps
    XLA from coalescing the collectives into one post-backward clump and
    (b) leaves each reduce data-independent from the still-outstanding
    backward compute so the latency-hiding scheduler can overlap them.

    Value contract: bitwise-identical results to the sequential
    `reduce_gradients` flush with quantization off, <= quantization error
    otherwise — the reordering only changes WHEN bytes move, never what
    is summed.  Runs INSIDE shard_map over ``axis_name``.
    """
    from .reduce import reduce_bucket_collective

    if op not in ("pmean", "psum"):
        raise ValueError(f"op={op!r}; expected pmean|psum")
    mean = op == "pmean"
    mode = quant_mode()

    leaves_kp, tdef = jax.tree_util.tree_flatten_with_path(grads)
    paths = [jax.tree_util.keystr(kp) for kp, _ in leaves_kp]
    leaves = [leaf for _, leaf in leaves_kp]

    order = list(emission_order) if emission_order is not None \
        else list(range(len(leaves)))
    if not _valid_order(order, len(leaves)):
        # surface through the OVL lint (raises under analyze_raise) before
        # the safe fallback so a corrupt plan cannot silently reorder
        _maybe_check(leaves, order, None)
        logger.warning("overlapped_reduce_gradients: emission_order is not "
                       "a permutation of %d leaves; using flat tree order",
                       len(leaves))
        order = list(range(len(leaves)))

    ordered_leaves = [leaves[i] for i in order]
    ordered_flags = [leaf_quantizable(paths[i], leaves[i].size, mode)
                     for i in order]
    buckets = plan_buckets(ordered_leaves, edconfig.comm_bucket_bytes,
                           ordered_flags)
    _maybe_check(ordered_leaves, order, buckets)

    reduced: List[Optional[jax.Array]] = [None] * len(leaves)
    token = None
    for bucket in buckets:
        flat = pack(ordered_leaves, bucket)
        if pin_chain and token is not None:
            flat, token = jax.lax.optimization_barrier((flat, token))
        out = reduce_bucket_collective(flat, bucket, axis_name, axis_size,
                                       mean, mode)
        token = out[:1]
        for j, leaf in unpack(out, bucket, ordered_leaves).items():
            reduced[order[j]] = leaf
    return jax.tree_util.tree_unflatten(tdef, reduced)


def chain_leaf_reduces(flat_leaves: Sequence, order: Sequence[int],
                       reduce_leaf_fn: Callable, pin_chain: bool = True):
    """Barrier-pinned chain over PER-LEAF reductions (the ZeRO paths,
    where each leaf needs its own reduce_scatter-or-all-reduce choice and
    bucket packing does not apply).

    ``reduce_leaf_fn(i, leaf)`` performs leaf i's collective; leaves are
    visited in ``order`` with each launch chained to the previous
    result's one-element token.  Returns the reduced leaves in ORIGINAL
    positions.
    """
    order = list(order)
    if not _valid_order(order, len(flat_leaves)):
        _maybe_check(list(flat_leaves), order, None)
        logger.warning("chain_leaf_reduces: order is not a permutation of "
                       "%d leaves; using flat tree order", len(flat_leaves))
        order = list(range(len(flat_leaves)))
    reduced: List[Optional[jax.Array]] = [None] * len(flat_leaves)
    token = None
    for i in order:
        leaf = flat_leaves[i]
        if pin_chain and token is not None:
            leaf, token = jax.lax.optimization_barrier((leaf, token))
        out = reduce_leaf_fn(i, leaf)
        reduced[i] = out
        token = jnp.ravel(out)[:1]
    return reduced


# ------------------------------------------- double-buffered accumulation

def _split_microbatches(batch, n_micro: int):
    split = []
    for x in batch:
        if x.shape[0] % n_micro:
            raise ValueError(
                f"grad_accum_microbatches={n_micro} does not divide the "
                f"local batch dimension {x.shape[0]}")
        split.append(x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:]))
    return tuple(split)


def accumulate_gradients(loss_fn: Callable, params, batch: Sequence,
                         *, axis_name: str, axis_size: int, n_micro: int,
                         reduce_tree: Optional[Callable] = None,
                         acc_shapes=None, overlapped: Optional[bool] = None,
                         op: str = "pmean",
                         emission_order: Optional[Sequence[int]] = None,
                         pin_chain: bool = True):
    """K-microbatch gradient accumulation with double-buffered reduction.

    Splits each batch array's leading dim into ``n_micro`` slices and runs
    a `lax.scan` whose carry holds the IN-FLIGHT gradient tree: iteration
    k barrier-pins (inflight_{k-1}, microbatch_k) together, then computes
    microbatch k's backward while reducing inflight_{k-1} — the two are
    data-independent, so XLA overlaps the wire time of one microbatch
    under the compute of the next.  ``reduce_tree(grads)`` defaults to
    this module's overlapped flush (or the sequential `reduce_gradients`
    when ``overlapped`` is False); ZeRO callers pass their own
    reduce_tree plus ``acc_shapes`` (a ShapeDtypeStruct tree of its
    output — reduce_scatter shrinks leaves, and calling the reducer on
    placeholders here would pollute the trace-time comm counters).

    Returns ``(mean_grads, mean_loss)`` — both averaged over the K
    microbatches AFTER reduction, with an accumulator fold order chosen
    to be identical between the overlapped and sequential variants
    (bitwise-equal with quantization off).  Runs INSIDE shard_map.
    """
    if n_micro < 1:
        raise ValueError(f"n_micro={n_micro}; expected >= 1")
    if overlapped is None:
        overlapped = bool(edconfig.comm_overlap)

    mbs = _split_microbatches(batch, n_micro)
    mb0 = tuple(x[0] for x in mbs)

    if reduce_tree is None:
        from .reduce import reduce_gradients

        order = emission_order
        if overlapped and order is None:
            order = grad_emission_order(loss_fn, params, *mb0)

        def reduce_tree(g):  # noqa: F811 - intentional default binding
            if overlapped:
                return overlapped_reduce_gradients(
                    g, axis_name, axis_size, op=op, emission_order=order,
                    pin_chain=pin_chain)
            return reduce_gradients(g, axis_name, axis_size, op=op)

    loss0, g0 = jax.value_and_grad(loss_fn)(params, *mb0)
    if n_micro == 1:
        return reduce_tree(g0), loss0

    if acc_shapes is None:
        acc = jax.tree_util.tree_map(
            lambda g: jnp.zeros(jnp.shape(g), jnp.result_type(g)), g0)
    else:
        acc = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), acc_shapes)
    rest = tuple(x[1:] for x in mbs)

    if overlapped:
        def body(carry, mb):
            inflight, acc, loss_acc = carry
            if pin_chain:
                inflight, mb = jax.lax.optimization_barrier((inflight, mb))
            loss_k, g_k = jax.value_and_grad(loss_fn)(params, *mb)
            acc = jax.tree_util.tree_map(jnp.add, acc, reduce_tree(inflight))
            return (g_k, acc, loss_acc + loss_k), None

        (last_g, acc, loss_acc), _ = jax.lax.scan(
            body, (g0, acc, loss0), rest)
        acc = jax.tree_util.tree_map(jnp.add, acc, reduce_tree(last_g))
    else:
        acc = jax.tree_util.tree_map(jnp.add, acc, reduce_tree(g0))

        def body(carry, mb):
            acc, loss_acc = carry
            loss_k, g_k = jax.value_and_grad(loss_fn)(params, *mb)
            acc = jax.tree_util.tree_map(jnp.add, acc, reduce_tree(g_k))
            return (acc, loss_acc + loss_k), None

        (acc, loss_acc), _ = jax.lax.scan(body, (acc, loss0), rest)

    grads = jax.tree_util.tree_map(lambda a: a / n_micro, acc)
    return grads, loss_acc / n_micro
