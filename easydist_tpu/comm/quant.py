"""Block-wise-scaled quantized reduction collectives (EQuARX-style).

EQuARX (arxiv 2506.17615) shows a block-scaled quantized all-reduce inside
XLA recovers most of the interconnect bandwidth at negligible quality
loss.  XLA's collective primitives are not user-extensible, so the same
two-pass scheme is expressed here as a portable collective program over
`jax.lax` primitives (the 2112.01075 shape: redistribution as collective
programs a cost model can price):

  pass 1 (reduce-scatter hop)   quantize the full local vector block-wise
                                (int8 payload + one f32 scale per block),
                                `all_to_all` so device i receives every
                                peer's chunk i, dequantize and sum in f32
                                in fixed peer order -> exact-order shard
  pass 2 (all-gather hop)       re-quantize the reduced shard, `all_gather`
                                payload+scales, dequantize everywhere

Wire bytes per device drop from ``2*(n-1)/n * 4B`` to about
``2*(n-1)/n * (1 + 4/block)B`` per element — ~3.9x at block=256.  Both
passes round with `jnp.rint` (half-to-even) and reduce in a fixed peer
order, so results are deterministic across runs and identical on every
device.  The int8 payload never carries arithmetic on the wire (sums happen
in f32 after dequantize), so there is no accumulator-overflow regime.

The ``"bf16"`` mode is the degenerate single-pass form: cast, reduce,
cast back — 2x wire saving, no block scales.

When quantization is disabled (``comm_quant_dtype="none"``) every wrapper
falls through to the exact `jax.lax` collective, bitwise-identical to the
emission that predates this subsystem.
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from easydist_tpu import config as edconfig

_QMAX_INT8 = 127.0
_VALID_MODES = ("none", "int8", "bf16")


def quant_mode() -> str:
    """The configured wire dtype, validated ("none" | "int8" | "bf16")."""
    mode = (edconfig.comm_quant_dtype or "none").lower()
    if mode not in _VALID_MODES:
        raise ValueError(
            f"comm_quant_dtype={edconfig.comm_quant_dtype!r}; expected one "
            f"of {_VALID_MODES}")
    return mode


def comm_enabled() -> bool:
    """True when any comm transformation (quantization OR bucketing) is on;
    False means the grad paths must emit their pre-subsystem programs."""
    return quant_mode() != "none" or edconfig.comm_bucket_bytes > 0


def leaf_quantizable(path: str, numel: int,
                     mode: Optional[str] = None) -> bool:
    """Per-leaf opt-out: sensitive leaves (norm scales, biases — anything
    matching `comm_quant_skip`) and tiny leaves (below
    `comm_quant_min_numel`, where padding + scale overhead eats the saving)
    stay at full precision."""
    mode = quant_mode() if mode is None else mode
    if mode == "none":
        return False
    if numel < edconfig.comm_quant_min_numel:
        return False
    pat = edconfig.comm_quant_skip
    if pat and re.search(pat, path, re.IGNORECASE):
        return False
    return True


# ------------------------------------------------------------- block scaling

def quantize_blockwise(flat: jax.Array, block: int
                       ) -> Tuple[jax.Array, jax.Array]:
    """1-D f32 vector (size % block == 0) -> (int8 payload, f32 per-block
    scales).  All-zero blocks get scale 1.0 so dequantize is exact."""
    xb = flat.reshape(-1, block)
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / _QMAX_INT8,
                      jnp.ones_like(amax))
    q = jnp.clip(jnp.rint(xb / scale), -_QMAX_INT8, _QMAX_INT8)
    return q.astype(jnp.int8).reshape(-1), scale.astype(jnp.float32).reshape(-1)


def dequantize_blockwise(q: jax.Array, scales: jax.Array,
                         block: int) -> jax.Array:
    return (q.astype(jnp.float32).reshape(-1, block)
            * scales.reshape(-1, 1)).reshape(-1)


def _pad_flat(flat: jax.Array, multiple: int) -> Tuple[jax.Array, int]:
    pad = (-flat.size) % multiple
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def int8_payload_bytes(numel: int, block: int) -> float:
    """Wire payload of a block-quantized vector: int8 values + one f32
    scale per block (padding to the block grid included)."""
    padded = numel + ((-numel) % block)
    return padded * 1.0 + (padded // block) * 4.0


# ------------------------------------------------------- quantized collectives
#
# All of these run INSIDE shard_map over `axis_name` (the dp.py / region
# emission context).  `axis_size` is static (mesh.shape[axis]).

def quantized_psum(x: jax.Array, axis_name: str, axis_size: int, *,
                   block: Optional[int] = None,
                   mean: bool = False) -> jax.Array:
    """Two-pass block-scaled int8 all-reduce; same shape/dtype as `x`.
    `mean=True` folds the /n into the reduced shard BEFORE the second
    quantization pass (better scale utilization than dividing after)."""
    n = axis_size
    if n <= 1:
        return x / n if mean else x
    block = block or edconfig.comm_quant_block
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    numel = flat.size
    flat, _ = _pad_flat(flat, n * block)
    chunk = flat.size // n

    # pass 1: exchange quantized chunks; device i ends with reduced chunk i
    q, s = quantize_blockwise(flat, block)
    q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)
    s = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)
    contrib = dequantize_blockwise(q, s, block).reshape(n, chunk)
    reduced = jnp.sum(contrib, axis=0)  # fixed peer order: deterministic
    if mean:
        reduced = reduced / n

    # pass 2: gather re-quantized shards back to every device
    q2, s2 = quantize_blockwise(reduced, block)
    q2 = jax.lax.all_gather(q2, axis_name, axis=0, tiled=True)
    s2 = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    out = dequantize_blockwise(q2, s2, block)
    return out[:numel].reshape(shape).astype(dtype)


def quantized_psum_scatter(x: jax.Array, axis_name: str, axis_size: int, *,
                           scatter_dim: int = 0,
                           block: Optional[int] = None,
                           mean: bool = False) -> jax.Array:
    """Block-scaled int8 reduce_scatter (tiled): device i gets the reduced
    slice i along `scatter_dim` — the single-hop half of quantized_psum."""
    n = axis_size
    if n <= 1:
        return x / n if mean else x
    block = block or edconfig.comm_quant_block
    dtype = x.dtype
    if scatter_dim != 0:
        x = jnp.moveaxis(x, scatter_dim, 0)
    assert x.shape[0] % n == 0, (x.shape, n)
    shard_shape = (x.shape[0] // n,) + x.shape[1:]
    parts = x.astype(jnp.float32).reshape(n, -1)  # row j = slice j
    cols = parts.shape[1]
    pad = (-cols) % block
    if pad:
        parts = jnp.pad(parts, ((0, 0), (0, pad)))
    # (cols+pad) % block == 0: every quant block lies inside one row, so a
    # row's scales travel with its payload through the same all_to_all
    q, s = quantize_blockwise(parts.reshape(-1), block)
    q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)
    s = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)
    contrib = dequantize_blockwise(q, s, block).reshape(n, cols + pad)
    reduced = jnp.sum(contrib, axis=0)
    if pad:
        reduced = reduced[:cols]
    if mean:
        reduced = reduced / n
    out = reduced.reshape(shard_shape).astype(dtype)
    if scatter_dim != 0:
        out = jnp.moveaxis(out, 0, scatter_dim)
    return out


def bf16_psum(x: jax.Array, axis_name: str, *, mean: bool = False,
              axis_size: int = 1) -> jax.Array:
    """Half-width wire: reduce a bf16 cast, cast back."""
    r = jax.lax.psum(x.astype(jnp.bfloat16), axis_name)
    r = r.astype(x.dtype)
    return r / axis_size if mean else r


def bf16_psum_scatter(x: jax.Array, axis_name: str, *, scatter_dim: int = 0,
                      mean: bool = False, axis_size: int = 1) -> jax.Array:
    r = jax.lax.psum_scatter(x.astype(jnp.bfloat16), axis_name,
                             scatter_dimension=scatter_dim, tiled=True)
    r = r.astype(x.dtype)
    return r / axis_size if mean else r
