"""Gradient-synchronization entry points for the parallel layer.

These are what `parallel/dp.py` (ddp/zero2/zero3) and the auto path's
partial-region fences call instead of raw `jax.lax` collectives.  Contract:

  * with the subsystem DISABLED (``comm_quant_dtype="none"`` and
    ``comm_bucket_bytes=0``, the defaults) every function emits exactly the
    pre-subsystem collective — same primitive, same operands — so compiled
    programs are bitwise-identical to the historical emission;
  * with it enabled, leaves are bucketed (fewer launches) and/or
    block-quantized on the wire (fewer bytes), with per-leaf opt-out for
    sensitive tensors (``comm_quant_skip``) and exact fp32 for tiny leaves;
  * every launch is recorded in `comm_counters` at trace time, wire bytes
    priced with the same ring closed forms as the solver's cost model.

All functions run INSIDE shard_map over `axis_name`.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from easydist_tpu import config as edconfig

from .bucketer import bucketed_reduce
from .counters import (comm_counters, ring_all_gather_bytes,
                       ring_all_reduce_bytes, ring_reduce_scatter_bytes)
from .quant import (bf16_psum, bf16_psum_scatter, comm_enabled,
                    int8_payload_bytes, leaf_quantizable, quant_mode,
                    quantized_psum, quantized_psum_scatter)


def _record_all_reduce(numel: int, itemsize: int, n: int, mode: str,
                       quantized: bool, fallback: bool = False,
                       bucketed_leaves: int = 0) -> None:
    full = ring_all_reduce_bytes(numel * 4.0, n)
    if not quantized:
        wire = ring_all_reduce_bytes(numel * float(itemsize), n)
    elif mode == "bf16":
        wire = ring_all_reduce_bytes(numel * 2.0, n)
    else:  # int8 two-pass: RS hop + AG hop of (payload + scales)
        payload = int8_payload_bytes(numel, edconfig.comm_quant_block)
        wire = (ring_reduce_scatter_bytes(payload, n)
                + ring_all_gather_bytes(payload, n))
    comm_counters.record(bytes_on_wire=wire, bytes_fp32_equiv=full,
                         quantized=quantized, fallback=fallback,
                         bucketed_leaves=bucketed_leaves)


def _record_reduce_scatter(numel: int, itemsize: int, n: int, mode: str,
                           quantized: bool, fallback: bool = False) -> None:
    full = ring_reduce_scatter_bytes(numel * 4.0, n)
    if not quantized:
        wire = ring_reduce_scatter_bytes(numel * float(itemsize), n)
    elif mode == "bf16":
        wire = ring_reduce_scatter_bytes(numel * 2.0, n)
    else:
        payload = int8_payload_bytes(numel, edconfig.comm_quant_block)
        wire = ring_reduce_scatter_bytes(payload, n)
    comm_counters.record(bytes_on_wire=wire, bytes_fp32_equiv=full,
                         quantized=quantized, fallback=fallback)


# --------------------------------------------------------------- tree reduce

def reduce_bucket_collective(flat, bucket, axis_name: str, axis_size: int,
                             mean: bool, mode: str):
    """One bucket's reduction collective (block-scaled int8 / bf16 / exact
    fp32 per ``bucket.quantize`` and ``mode``), with counter recording —
    shared by the sequential flush below and the backward-ordered
    overlapped flush (`comm.overlap`)."""
    n = axis_size
    fused = len(bucket.indices)
    if bucket.quantize and mode == "int8":
        out = quantized_psum(flat, axis_name, n, mean=mean)
        _record_all_reduce(flat.size, flat.dtype.itemsize, n, mode,
                           quantized=True, bucketed_leaves=fused)
    elif bucket.quantize and mode == "bf16":
        out = bf16_psum(flat, axis_name, mean=mean, axis_size=n)
        _record_all_reduce(flat.size, flat.dtype.itemsize, n, mode,
                           quantized=True, bucketed_leaves=fused)
    else:
        out = (jax.lax.pmean(flat, axis_name) if mean
               else jax.lax.psum(flat, axis_name))
        _record_all_reduce(flat.size, flat.dtype.itemsize, n, mode,
                           quantized=False, bucketed_leaves=fused)
    return out


def reduce_gradients(grads, axis_name: str, axis_size: int,
                     op: str = "pmean", emission_order=None):
    """Synchronize a gradient pytree over `axis_name` (the DDP path).

    Disabled -> one `jax.lax.pmean`/`psum` per leaf, the exact historical
    program.  Enabled -> leaves are partitioned by quantizability, packed
    into `comm_bucket_bytes` buckets, and each bucket pays ONE collective
    (block-scaled int8, bf16, or exact fp32 per its group).

    With ``edconfig.comm_overlap`` set the flush is handed to
    `comm.overlap.overlapped_reduce_gradients`: buckets are planned in
    backward EMISSION order (``emission_order``, a flat-leaf permutation
    from `comm.overlap.grad_emission_order`) and launched as a
    barrier-pinned chain so XLA can slide each collective under the
    remaining backward compute.  Value-identical to the sequential flush
    (bitwise when quantization is off).
    """
    if op not in ("pmean", "psum"):
        raise ValueError(f"op={op!r}; expected pmean|psum")
    mean = op == "pmean"
    n = axis_size
    mode = quant_mode()

    if edconfig.comm_overlap:
        from .overlap import overlapped_reduce_gradients

        return overlapped_reduce_gradients(grads, axis_name, axis_size,
                                           op=op,
                                           emission_order=emission_order)

    if not comm_enabled():
        # exact fp32 fallback: bitwise-identical to the pre-subsystem
        # tree_map emission (one collective per leaf, no repacking)
        def red(g):
            _record_all_reduce(g.size, jnp.dtype(g.dtype).itemsize, n, mode,
                               quantized=False, fallback=True)
            return (jax.lax.pmean(g, axis_name) if mean
                    else jax.lax.psum(g, axis_name))

        return jax.tree_util.tree_map(red, grads)

    leaves_kp, tdef = jax.tree_util.tree_flatten_with_path(grads)
    paths = [jax.tree_util.keystr(kp) for kp, _ in leaves_kp]
    leaves = [leaf for _, leaf in leaves_kp]
    flags = [leaf_quantizable(p, leaf.size, mode)
             for p, leaf in zip(paths, leaves)]

    def reduce_bucket(flat, bucket):
        fused = len(bucket.indices)
        if bucket.quantize and mode == "int8":
            out = quantized_psum(flat, axis_name, n, mean=mean)
            _record_all_reduce(flat.size, flat.dtype.itemsize, n, mode,
                               quantized=True, bucketed_leaves=fused)
        elif bucket.quantize and mode == "bf16":
            out = bf16_psum(flat, axis_name, mean=mean, axis_size=n)
            _record_all_reduce(flat.size, flat.dtype.itemsize, n, mode,
                               quantized=True, bucketed_leaves=fused)
        else:
            out = (jax.lax.pmean(flat, axis_name) if mean
                   else jax.lax.psum(flat, axis_name))
            _record_all_reduce(flat.size, flat.dtype.itemsize, n, mode,
                               quantized=False, bucketed_leaves=fused)
        return out

    reduced = bucketed_reduce(leaves, flags, edconfig.comm_bucket_bytes,
                              reduce_bucket)
    return jax.tree_util.tree_unflatten(tdef, reduced)


# --------------------------------------------------------------- leaf reduce

def all_reduce_grad(g, axis_name: str, axis_size: int, *, mean: bool = True,
                    path: str = ""):
    """One leaf's all-reduce (the ZeRO replicated-moment path)."""
    mode = quant_mode()
    if leaf_quantizable(path, g.size, mode):
        _record_all_reduce(g.size, g.dtype.itemsize, axis_size, mode,
                           quantized=True)
        if mode == "int8":
            return quantized_psum(g, axis_name, axis_size, mean=mean)
        return bf16_psum(g, axis_name, mean=mean, axis_size=axis_size)
    _record_all_reduce(g.size, g.dtype.itemsize, axis_size, mode,
                       quantized=False, fallback=(mode == "none"))
    return (jax.lax.pmean(g, axis_name) if mean
            else jax.lax.psum(g, axis_name))


def reduce_scatter_grad(g, axis_name: str, axis_size: int, *,
                        scatter_dim: int = 0, mean: bool = True,
                        path: str = ""):
    """One leaf's reduce_scatter over `scatter_dim` (tiled), the
    ZeRO-2/3 sharded-grad path.  Returns the local reduced shard."""
    mode = quant_mode()
    if leaf_quantizable(path, g.size, mode):
        _record_reduce_scatter(g.size, g.dtype.itemsize, axis_size, mode,
                               quantized=True)
        if mode == "int8":
            return quantized_psum_scatter(g, axis_name, axis_size,
                                          scatter_dim=scatter_dim, mean=mean)
        return bf16_psum_scatter(g, axis_name, scatter_dim=scatter_dim,
                                 mean=mean, axis_size=axis_size)
    _record_reduce_scatter(g.size, g.dtype.itemsize, axis_size, mode,
                           quantized=False, fallback=(mode == "none"))
    out = jax.lax.psum_scatter(g, axis_name, scatter_dimension=scatter_dim,
                               tiled=True)
    return out / axis_size if mean else out


# ----------------------------------------------------------- region fences

def fence_psum(val, axis_name: str, axis_size: int):
    """The deferred-reduction all-reduce at a partial-region fence (auto
    path).  No leaf path exists here; quantizability gates on size only."""
    mode = quant_mode()
    if leaf_quantizable("", val.size, mode):
        _record_all_reduce(val.size, val.dtype.itemsize, axis_size, mode,
                           quantized=True)
        if mode == "int8":
            return quantized_psum(val, axis_name, axis_size)
        return bf16_psum(val, axis_name)
    _record_all_reduce(val.size, val.dtype.itemsize, axis_size, mode,
                       quantized=False, fallback=(mode == "none"))
    return jax.lax.psum(val, axis_name)


def fence_psum_scatter(val, axis_name: str, axis_size: int,
                       scatter_dim: int):
    """The P -> S fence: reduce_scatter at half the all-reduce bytes."""
    mode = quant_mode()
    if leaf_quantizable("", val.size, mode):
        _record_reduce_scatter(val.size, val.dtype.itemsize, axis_size, mode,
                               quantized=True)
        if mode == "int8":
            return quantized_psum_scatter(val, axis_name, axis_size,
                                          scatter_dim=scatter_dim)
        return bf16_psum_scatter(val, axis_name, scatter_dim=scatter_dim)
    _record_reduce_scatter(val.size, val.dtype.itemsize, axis_size, mode,
                           quantized=False, fallback=(mode == "none"))
    return jax.lax.psum_scatter(val, axis_name,
                                scatter_dimension=scatter_dim, tiled=True)
