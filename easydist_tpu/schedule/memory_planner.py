"""Graph-level memory planning from liveness + solved strategies.

Reference counterparts: schedule/lifetime_info.py (ASAP/ALAP lifetimes),
schedule/efficient_memory_scheduler.py (skyline addresses), and the
runtime ownership checker (compile_auto.py:269-351).  Sizes honor the solved
per-axis placements: a tensor sharded on an axis of size n costs 1/n of its
bytes per device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from easydist_tpu import native
from easydist_tpu.metashard.metair import (_DTYPE_BYTES, MetaGraph,
                                           NodeStrategy)


@dataclass
class MemoryPlan:
    var_names: List[str]
    starts: np.ndarray
    ends: np.ndarray
    sizes: np.ndarray  # per-device bytes under the solved placements
    offsets: np.ndarray
    peak_bytes: int  # skyline peak (achievable packing)
    peak_live_bytes: int  # sum-of-live lower bound

    def validate(self) -> List:
        return native.check_plan(self.starts, self.ends, self.sizes,
                                 self.offsets)


def _sharded_bytes(var, placements, axis_sizes) -> int:
    """Per-device bytes under the given per-axis placements, in exact
    integer bytes: shard dims divide in ELEMENTS, rounded UP per shard (a
    non-divisible dim leaves ceil(d/n) elements on the widest device — the
    one whose peak matters), so skyline offsets stay element-aligned and
    never drift through fractional float accumulation."""
    shape = list(var.shape)
    for p, n in zip(placements, axis_sizes):
        if p is not None and p.is_shard() and n > 0 and p.dim < len(shape):
            shape[p.dim] = -(-shape[p.dim] // n)  # ceil division
    elems = 1
    for d in shape:
        elems *= int(d)
    return elems * _DTYPE_BYTES.get(var.dtype, 4)


def plan_graph_memory(graph: MetaGraph,
                      per_axis: Sequence[Dict[str, NodeStrategy]],
                      axis_sizes: Sequence[int]) -> MemoryPlan:
    """Compute buffer lifetimes over the op schedule and a skyline packing.

    `per_axis` is the solver output per mesh axis (may be empty dicts);
    tensor sizes are divided by each axis that shards them.
    """
    # lifetime: producer op index -> last consumer op index
    op_index = {node.name: i for i, node in enumerate(graph.ops)}
    intervals = []  # (var, start, end)
    out_names = {v.name for v in graph.outputs}
    n_ops = len(graph.ops)

    def var_placements(var):
        node = var.producer
        if node is None:
            return [None] * len(axis_sizes)
        out = []
        for chosen in per_axis:
            s = chosen.get(node.name)
            if s is None or var.producer_idx >= len(s.out_placements):
                out.append(None)
            else:
                out.append(s.out_placements[var.producer_idx])
        return out

    seen = set()
    for i, node in enumerate(graph.ops):
        for var in node.outvars:
            if var is None or var.name in seen:
                continue
            seen.add(var.name)
            last = i
            for consumer, _ in var.consumers:
                last = max(last, op_index.get(consumer.name, i))
            if var.name in out_names:
                last = n_ops - 1
            intervals.append((var, i, last))
    # graph inputs live from step 0 until their last consumer (pinned to
    # the end when they escape directly as graph outputs)
    for node in graph.inputs:
        for var in node.outvars:
            if var is None or var.name in seen:
                continue
            seen.add(var.name)
            last = 0
            for consumer, _ in var.consumers:
                last = max(last, op_index.get(consumer.name, 0))
            if var.name in out_names:
                last = n_ops - 1
            intervals.append((var, 0, last))

    names = [v.name for v, _, _ in intervals]
    starts = np.array([s for _, s, _ in intervals], dtype=np.int64)
    ends = np.array([e for _, _, e in intervals], dtype=np.int64)
    sizes = np.array([max(_sharded_bytes(v, var_placements(v), axis_sizes),
                          1)
                      for v, _, _ in intervals], dtype=np.int64)

    offsets, peak = native.skyline_plan(starts, ends, sizes)
    lower = native.peak_live(starts, ends, sizes)
    return MemoryPlan(names, starts, ends, sizes, offsets, peak, lower)
