"""Solver-chosen rematerialization on the traced (already-differentiated)
jaxpr — the TPU re-expression of the reference's memory-opt subsystem
(profile -> plan -> replay, easydist/torch/compile_auto.py:353-453 and the
ILP address model, torch/schedule/ilp_memory_scheduler.py:25).

On TPU, XLA owns addresses, so the decision surface is *what to keep live*:
when the planned per-device peak exceeds the HBM cap, this pass picks
long-lived activations (live across the forward->backward boundary) and
rewrites the program so their far consumers RECOMPUTE them from values that
are alive anyway — the block-boundary residual stream, parameters — instead
of keeping them resident.  That is exactly `jax.checkpoint`-per-block
semantics, but chosen by the compiler from the liveness profile, after
autodiff, with no user annotation (`jax.checkpoint` itself cannot be
applied post-hoc: the user's step already contains its own value_and_grad).

Recomputed chains read their sources through `jax.lax.optimization_barrier`
so XLA's CSE cannot fold the duplicate back into the original (the same
mechanism jax.remat lowering uses).

The cost dimension is recompute-seconds vs liveness-bytes: chains are
capped in length and priced by a FLOP/HBM proxy; candidates are taken
largest-resident-bytes-per-recompute-second first.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from easydist_tpu import config as edconfig

logger = logging.getLogger(__name__)

# primitives whose equations may be re-executed: anything flat and pure.
# Equations carrying sub-jaxprs (control flow, remat regions, sharded
# calls) are not chain material — recomputing them wholesale would nest
# arbitrarily deep.
_BANNED_PARAM_KEYS = ("jaxpr", "call_jaxpr", "branches", "cond_jaxpr",
                      "body_jaxpr", "fun_jaxpr")

# XLA fusion model for liveness sizing (validated against memory_analysis
# on v5e — charging every intermediate overstated GPT-2's peak 3.4x):
# - compute-pointwise outputs whose consumers are all fusable/reduce ops
#   stay inside one fusion (softmax's exp feeding reduce+div) — never in HBM
# - layout/convert outputs with a single consumer fold into the consumer's
#   operand read (bf16 converts and transposes feeding the MXU)
_POINTWISE_PRIMS = frozenset((
    "tanh", "exp", "log", "logistic", "rsqrt", "sqrt", "neg", "abs", "sign",
    "floor", "ceil", "round", "erf", "erf_inv", "erfc", "sin", "cos",
    "integer_pow", "pow", "add", "sub", "mul", "div", "max", "min", "rem",
    "and", "or", "xor", "not", "select_n", "eq", "ne", "lt", "le", "gt",
    "ge", "iota", "copy", "stop_gradient", "is_finite", "clamp", "add_any",
    "real", "imag", "logaddexp",
))
_LAYOUT_PRIMS = frozenset((
    "convert_element_type", "broadcast_in_dim", "transpose", "reshape",
    "expand_dims", "squeeze", "rev",
))
_REDUCE_PRIMS = frozenset((
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "argmax",
    "argmin", "reduce_and", "reduce_or",
))
_FUSABLE_PRIMS = _POINTWISE_PRIMS | _LAYOUT_PRIMS  # remat-chain material


def _eqn_recomputable(eqn) -> bool:
    if any(k in eqn.params for k in _BANNED_PARAM_KEYS):
        return False
    return True


def candidate_score(nbytes: float, recompute_s: float) -> float:
    """The remat ranking metric: resident bytes reclaimed per second of
    recompute — candidates are taken largest-first.  Shared with the
    analyzer's MEM004 budget advisory (analyze/memory_rules.py) so the
    advisory names exactly the candidates this planner would pick."""
    return nbytes / (1e-6 + recompute_s)


def _eqn_flops(eqn) -> float:
    """Crude per-equation recompute cost proxy (seconds are derived by the
    caller).  dot_general: 2*M*N*K; conv: treated as expensive; everything
    else: output elements (elementwise on the VPU)."""
    out_elems = sum(int(np.prod(v.aval.shape)) for v in eqn.outvars
                    if hasattr(v.aval, "shape"))
    name = eqn.primitive.name
    if name == "dot_general":
        dims = eqn.params.get("dimension_numbers")
        lhs = eqn.invars[0].aval.shape
        contract = 1
        if dims:
            for d in dims[0][0]:
                contract *= lhs[d]
        return 2.0 * out_elems * max(contract, 1)
    if "conv" in name:
        return 50.0 * out_elems
    return float(out_elems)


@dataclass
class RematPlan:
    """recompute: consumer eqn idx -> ordered chain eqn idxs to (re)execute.
    overlay_last_use: chain eqn idx -> last consumer idx that reads its
    outputs (emission shares recomputed values between consumers and evicts
    after this point).  redirected: consumer idx -> var names read from the
    overlay instead of the original environment."""
    recompute: Dict[int, List[int]] = field(default_factory=dict)
    overlay_last_use: Dict[int, int] = field(default_factory=dict)
    n_remat_vars: int = 0
    base_peak: int = 0
    predicted_peak: int = 0
    recompute_seconds: float = 0.0

    def __bool__(self):
        return bool(self.recompute)


class _Liveness:
    """Mutable interval model over the jaxpr's vars (one interval per var,
    op-index granularity, sizes honoring the solved per-axis shardings)."""

    def __init__(self, jaxpr, names, per_axis, axis_sizes, state_io_names):
        from jax.extend import core as jex_core

        self.jaxpr = jaxpr
        self.n_ops = max(len(jaxpr.eqns), 1)
        self.producer: Dict[object, int] = {}
        self.consumers: Dict[object, List[int]] = {}
        self.size: Dict[object, int] = {}
        self.start: Dict[object, int] = {}
        self.end: Dict[object, int] = {}
        self.is_invar: Set[object] = set()

        def sharded_bytes(var, strategy, out_idx) -> int:
            aval = var.aval
            if not hasattr(aval, "shape"):
                return 0
            size = float(np.prod(aval.shape, dtype=np.float64)
                         ) * aval.dtype.itemsize
            for chosen, n in zip(per_axis, axis_sizes):
                s = chosen.get(strategy)
                if s is None or out_idx >= len(s.out_placements):
                    continue
                p = s.out_placements[out_idx]
                if p is not None and p.is_shard():
                    size /= n
            return max(int(size), 1)

        for var in list(jaxpr.invars) + list(jaxpr.constvars):
            self.producer[var] = -1
            self.is_invar.add(var)
            self.size[var] = sharded_bytes(var, names.name(var), 0)
            self.start[var] = 0
            self.end[var] = 0
        for idx, eqn in enumerate(jaxpr.eqns):
            for k, v in enumerate(eqn.outvars):
                self.producer[v] = idx
                self.size[v] = sharded_bytes(v, f"op{idx}", k)
                self.start[v] = idx
                self.end[v] = idx
            for v in eqn.invars:
                if isinstance(v, jex_core.Literal):
                    continue
                self.consumers.setdefault(v, []).append(idx)
                if v in self.end:
                    self.end[v] = max(self.end[v], idx)

        # XLA-fusion-aware sizing (see _POINTWISE/_LAYOUT_PRIMS above): an
        # output is fusion-internal — never materialized in HBM — when its
        # consumers sit in the same fusion neighborhood (temporally near)
        # and, for compute-pointwise ops, are themselves fusable/reduce ops.
        # A far consumer is a saved-for-backward residual: always charged.
        # The model still overestimates XLA's scheduler somewhat (duplicated
        # cheap ops, multi-output fusions) — the safe direction for an OOM
        # guard.
        out_set = {v for v in jaxpr.outvars
                   if not isinstance(v, jex_core.Literal)}
        transparent = _POINTWISE_PRIMS | _LAYOUT_PRIMS | _REDUCE_PRIMS
        window = 24
        for idx, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            if name not in _POINTWISE_PRIMS and name not in _LAYOUT_PRIMS:
                continue
            for v in eqn.outvars:
                if v in out_set:
                    continue
                cons = self.consumers.get(v, ())
                if not cons:
                    self.size[v] = 0
                    continue
                if max(cons) - idx > window:
                    continue  # saved for backward: materialized
                if name in _LAYOUT_PRIMS:
                    if len(cons) <= 1:
                        self.size[v] = 0
                elif all(jaxpr.eqns[j].primitive.name in transparent
                         for j in cons):
                    self.size[v] = 0

        # jaxpr outputs live to the end; donated state outputs alias their
        # paired input buffer (size 0) and pin the input to program end
        donated_in = {in_name for in_name in state_io_names.values()}
        out_names = {}
        for v in jaxpr.outvars:
            if not isinstance(v, jex_core.Literal) and v in self.end:
                self.end[v] = self.n_ops - 1
                out_names[names.name(v)] = v
        for out_name, in_name in state_io_names.items():
            v = out_names.get(out_name)
            if v is not None:
                self.size[v] = 0
        for var in self.is_invar:
            if names.name(var) in donated_in:
                self.end[var] = self.n_ops - 1

    def live_profile(self) -> np.ndarray:
        delta = np.zeros(self.n_ops + 1, dtype=np.int64)
        for v, s in self.start.items():
            e = self.end[v]
            if e < s:
                continue
            delta[s] += self.size[v]
            delta[e + 1] -= self.size[v]
        return np.cumsum(delta[:-1])


def plan_remat(closed_jaxpr, names, per_axis: Sequence[Dict],
               axis_sizes: Sequence[int], cap_bytes: int,
               state_io_names: Optional[Dict[str, str]] = None,
               banned_eqns: Optional[Set[int]] = None
               ) -> Optional[RematPlan]:
    """Greedy liveness-driven remat planning.  Returns None when the
    program already fits (or nothing rematerializable helps).
    `banned_eqns` (e.g. deferred-reduction region members, which are
    emitted inside one shard_map) may neither join recompute chains nor
    host recompute sites."""
    from jax.extend import core as jex_core

    banned_eqns = banned_eqns or set()
    jaxpr = closed_jaxpr.jaxpr
    if not jaxpr.eqns or cap_bytes <= 0:
        return None
    lv = _Liveness(jaxpr, names, per_axis, axis_sizes, state_io_names or {})
    profile = lv.live_profile()
    base_peak = int(profile.max())
    if base_peak <= cap_bytes:
        return None

    plan = RematPlan(base_peak=base_peak, predicted_peak=base_peak)
    max_chain = edconfig.remat_max_chain_len
    # seconds proxy for chain pricing; measured per-op times (PerfDB,
    # runtime/op_profile.py — ROADMAP #5) replace the FLOP proxy per eqn
    # when a profile exists for the op's signature
    flops_per_s = max(edconfig.peak_flops, 1.0)
    op_times: Dict[str, float] = {}
    if edconfig.use_op_cost_db:
        try:
            from easydist_tpu.runtime.op_profile import load_op_times

            op_times = load_op_times()
        except Exception:
            op_times = {}
    sig_cache: Dict[int, Optional[str]] = {}

    def eqn_seconds(e: int) -> float:
        if op_times:
            sig = sig_cache.get(e)
            if sig is None and e not in sig_cache:
                from easydist_tpu.jaxfront.interpreter import eqn_signature

                try:
                    sig = eqn_signature(jaxpr.eqns[e], names)
                except Exception:
                    sig = None
                sig_cache[e] = sig
            measured = op_times.get(sig) if sig else None
            if measured is not None:
                return measured
        return _eqn_flops(jaxpr.eqns[e]) / flops_per_s

    # vars whose far consumers have been redirected (no longer readable
    # past their shortened end)
    rematted: Set[object] = set()
    accounted_eqns: Set[int] = set()  # chain eqns already priced (unique)

    def build_chain(target, at: int) -> Optional[List[int]]:
        """Eqn indices (ascending = topological) whose re-execution at op
        `at` reproduces `target` from values alive at `at`."""
        chain: Set[int] = set()
        stack = [target]
        while stack:
            u = stack.pop()
            if isinstance(u, jex_core.Literal):
                continue
            if u is not target:
                if u in lv.is_invar:
                    continue
                if lv.end.get(u, -1) >= at and u not in rematted:
                    continue  # alive at the consumer: read, don't recompute
            e = lv.producer.get(u)
            if e is None or e < 0:
                continue
            if e in chain:
                continue
            if e in banned_eqns:
                return None
            eqn = jaxpr.eqns[e]
            if not _eqn_recomputable(eqn):
                return None
            chain.add(e)
            if len(chain) > max_chain:
                return None
            stack.extend(eqn.invars)
        return sorted(chain)

    def metric(profile) -> Tuple[int, int]:
        """(peak, bytes-x-ops area above cap): a commit that shaves a
        plateau point without moving the max is still progress."""
        return (int(profile.max()),
                int(np.maximum(profile - cap_bytes, 0).sum()))

    for _round in range(2048):
        profile = lv.live_profile()
        peak = int(profile.max())
        cur_metric = metric(profile)
        plan.predicted_peak = peak
        if peak <= cap_bytes:
            break
        t_star = int(profile.argmax())

        # candidates: eqn-produced vars resident across the peak whose far
        # consumers can recompute them
        cands: List[Tuple[float, object, int, List[int]]] = []
        for v, s in lv.start.items():
            if v in lv.is_invar or v in rematted or lv.size[v] == 0:
                continue
            if not (s < t_star < lv.end[v]):
                continue
            far = [j for j in lv.consumers.get(v, []) if j > t_star]
            if not far or len(far) > 4 \
                    or any(j in banned_eqns for j in far):
                continue
            chain = build_chain(v, min(far))
            if not chain:
                continue
            cost_s = sum(eqn_seconds(e) for e in chain)
            score = candidate_score(lv.size[v], cost_s)
            cands.append((score, v, t_star, chain))
            if len(cands) >= 256:
                break
        if not cands:
            logger.warning(
                "[remat] peak %.2f GiB still over cap %.2f GiB and no "
                "rematerializable candidates remain",
                peak / 2**30, cap_bytes / 2**30)
            break
        cands.sort(key=lambda c: -c[0])

        # try candidates best-first until one genuinely improves the
        # metric; rejections are per-round (a candidate useless at this
        # peak point may help after the peak moves)
        committed = False
        for _, v, t_cut, chain in cands:
            # snapshot for rollback: a remat whose recompute-span residency
            # outweighs the saving must not be committed
            saved_end = dict(lv.end)
            saved_recompute = {k: list(vv)
                               for k, vv in plan.recompute.items()}
            saved_last_use = dict(plan.overlay_last_use)
            saved_seconds = plan.recompute_seconds
            saved_accounted = set(accounted_eqns)

            far = [j for j in lv.consumers[v] if j > t_cut]
            near = [j for j in lv.consumers[v] if j <= t_cut]
            first_far, last_far = min(far), max(far)
            for j in far:
                merged = set(plan.recompute.get(j, ())) | set(chain)
                plan.recompute[j] = sorted(merged)
            for e in chain:
                plan.overlay_last_use[e] = max(
                    plan.overlay_last_use.get(e, -1), last_far)
                # overlay sharing executes a chain equation once even when
                # several committed vars' chains contain it — count unique
                if e not in accounted_eqns:
                    accounted_eqns.add(e)
                    plan.recompute_seconds += eqn_seconds(e)
            # model: original interval ends at the last near consumer; the
            # recomputed copy lives [first_far, last_far]; chain sources
            # read at first_far stay resident through last_far.  Chain
            # intermediates are transient inside the consumer's slot (XLA
            # frees them within the fused region) and are not charged.
            lv.end[v] = max(near) if near else lv.start[v]
            chain_set = set(chain)
            key = ("remat", v, first_far)
            lv.producer[key] = first_far
            lv.size[key] = lv.size.get(v, 0)
            lv.start[key] = first_far
            lv.end[key] = last_far
            for e in chain:
                for u in jaxpr.eqns[e].invars:
                    if isinstance(u, jex_core.Literal):
                        continue
                    if lv.producer.get(u, -1) in chain_set:
                        continue  # overlay-internal
                    if u in lv.end:
                        lv.end[u] = max(lv.end[u], last_far)

            new_metric = metric(lv.live_profile())
            logger.debug("[remat] round %d t*=%d chain=%d metric %s -> %s",
                         _round, t_star, len(chain), cur_metric, new_metric)
            if new_metric < cur_metric:
                rematted.add(v)
                committed = True
                break
            # roll back
            lv.end = saved_end
            lv.producer.pop(key, None)
            lv.size.pop(key, None)
            lv.start.pop(key, None)
            plan.recompute = saved_recompute
            plan.overlay_last_use = saved_last_use
            plan.recompute_seconds = saved_seconds
            accounted_eqns = saved_accounted
        if not committed:
            logger.info(
                "[remat] no candidate improves the profile at peak %.2f "
                "GiB (cap %.2f GiB); stopping with %d vars",
                peak / 2**30, cap_bytes / 2**30, len(rematted))
            break

    plan.n_remat_vars = len(rematted)
    if not plan.recompute:
        return None
    logger.info(
        "[remat] %d vars rematerialized across %d consumers: planned peak "
        "%.2f -> %.2f GiB (cap %.2f), est. recompute %.1f ms/step",
        plan.n_remat_vars, len(plan.recompute), plan.base_peak / 2**30,
        plan.predicted_peak / 2**30, cap_bytes / 2**30,
        plan.recompute_seconds * 1e3)
    return plan


def resolve_memory_cap(mesh) -> int:
    """Per-device HBM budget in bytes, with `memory_ratio` headroom
    applied uniformly (the solver's liveness constraint scales the same
    way — an explicit cap without the ratio would ship programs with none
    of the allocator headroom the ratio exists to provide).  Config wins
    when set (>0); 0 disables; the default (-1) asks the real device (TPU
    memory_stats bytes_limit).  Unknown (CPU virtual meshes) -> uncapped."""
    cap = edconfig.per_device_memory_cap
    if cap >= 0:
        return int(cap * edconfig.memory_ratio) if cap > 0 else 0
    try:
        dev = np.asarray(mesh.devices).flat[0]
        stats = dev.memory_stats()
        if stats:
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            if limit:
                return int(limit * edconfig.memory_ratio)
    except Exception:
        pass
    return 0
