"""Memory planning & analysis (reference: easydist/torch/schedule/).

On TPU, XLA owns buffer addresses, so the planner's outputs are *analysis
and policy*: per-strategy peak-memory estimates (feeding the solver's memory
cap), a skyline packing that bounds what any allocator could achieve, and a
lifetime-overlap validator (the op_mem_checker analog).  The heavy loops run
in the native C++ planner (easydist_tpu/native)."""

from .memory_planner import plan_graph_memory, MemoryPlan  # noqa: F401
