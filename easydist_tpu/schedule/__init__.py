"""Memory planning & analysis (reference: easydist/torch/schedule/).

On TPU, XLA owns buffer addresses, so the planner's outputs are *analysis
and policy*: per-strategy peak-memory estimates (feeding the solver's memory
cap), a skyline packing that bounds what any allocator could achieve, and a
lifetime-overlap validator (the op_mem_checker analog).  The heavy loops run
in the native C++ planner (easydist_tpu/native).

Everything this package plans is statically re-audited by
`easydist_tpu.analyze` layer 3: the MEM rule family re-derives lifetimes
and sharded sizes independently, gates the predicted peak against the HBM
budget, and audits `remat.plan_remat` rewrites (docs/ANALYZE.md)."""

from .memory_planner import plan_graph_memory, MemoryPlan  # noqa: F401
