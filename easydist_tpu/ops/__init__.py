"""Pallas TPU kernels for hot ops."""

from .flash_attention import (chunk_attention, decode_attention,  # noqa: F401
                              flash_attention, flash_decode_attention,
                              flash_paged_decode_attention,
                              flash_paged_decode_quant_attention,
                              gather_pages, kv_dequantize, kv_quantize,
                              paged_decode_attention)
