"""Flash attention as Pallas TPU kernels (forward AND fused backward).

Blockwise online-softmax attention with **streamed K/V**: the K/V (and in
the dK/dV pass, Q/dO) blocks ride the innermost grid dimension, so VMEM
residency per program is O(block) — independent of sequence length — and
long-context (8k-32k) sequences fit the ~16 MB VMEM budget.  Statistics
(running max / denominator) and the output accumulator persist in f32 VMEM
scratch across the innermost grid steps (TPU grids iterate sequentially, so
scratch carries between iterations; ``@pl.when(ki == 0)`` initialises,
``@pl.when(ki == last)`` writes out).

The backward is the FlashAttention-2 recipe: the forward saves the per-row
logsumexp, ``delta = rowsum(dO * O)`` is precomputed in XLA, then two
kernels stream blocks — dQ accumulates over K-blocks, dK/dV accumulate over
Q-blocks — recomputing ``P = exp(S - lse)`` per block.  No [T, T] residual
survives the forward.  The ring variant composes this kernel with the
ppermute loop in parallel/ring_attention.py.

Reference scenario: the reference relies on torch SDPA/cutlass kernels
(benchmark/torch/model/gpt.py attention); this is the TPU-native analog.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from easydist_tpu.utils.jax_compat import tpu_compiler_params

_NEG_INF = -1e30


def _pick_block(block: int, t: int) -> int:
    b = min(block, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _causal_mask(s, qi, ki, block_q, block_k):
    bq, bk = s.shape
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _kv_index_map(causal, block_q, block_k):
    """K/V block index for grid (bh, qi, ki).  Causal: steps strictly above
    the diagonal clamp to the diagonal block — Pallas skips the DMA when
    the mapped index repeats, so fully-masked blocks cost no HBM traffic
    (the kernel's @pl.when already skips their compute)."""
    if not causal:
        return lambda bh, qi, ki: (bh, ki, 0)
    return lambda bh, qi, ki: (
        bh, jnp.minimum(ki, ((qi + 1) * block_q - 1) // block_k), 0)


def _q_index_map(causal, block_q, block_k):
    """Q-side block index for grid (bh, ki, qb) (dK/dV pass).  Causal: Q
    blocks strictly above the K block's first row are fully masked — clamp
    to the first contributing block so leading masked steps re-use one
    fetch."""
    if not causal:
        return lambda bh, ki, qb: (bh, qb, 0)
    return lambda bh, ki, qb: (
        bh, jnp.maximum(qb, (ki * block_k) // block_q), 0)


# ---------------------------------------------------------------- forward


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, o_scr, m_scr, l_scr,
                  *, causal: bool, scale: float, block_q: int, block_k: int,
                  n_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        o_scr[...] = jnp.zeros_like(o_scr)

    # a K block strictly above the diagonal contributes nothing
    run = (ki * block_k <= (qi + 1) * block_q - 1) if causal else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale        # [bq, d]
        k_blk = k_ref[0].astype(jnp.float32)            # [bk, d]
        v_blk = v_ref[0].astype(jnp.float32)
        s = q @ k_blk.T                                 # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev = m_scr[...]                             # [bq, 1]
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)                 # [bq, 1]
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[...] = o_scr[...] * alpha + p @ v_blk

    @pl.when(ki == n_k - 1)
    def _write():
        l_safe = jnp.maximum(l_scr[...], 1e-30)         # [bq, 1]
        o_ref[0] = (o_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[...] + jnp.log(l_safe)).astype(jnp.float32)


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    bq = _pick_block(block_q, t_q)
    bk = _pick_block(block_k, t_k)
    n_q, n_k = t_q // bq, t_k // bk

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)

    kernel = functools.partial(_flash_kernel, causal=causal, scale=scale,
                               block_q=bq, block_k=bk, n_k=n_k)
    kv_map = _kv_index_map(causal, bq, bk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), kv_map),
            pl.BlockSpec((1, bk, d), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t_q, d), lse.reshape(b * h, t_q)


# --------------------------------------------------------------- backward


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, causal: bool, scale: float,
                         block_q: int, block_k: int, n_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (ki * block_k <= (qi + 1) * block_q - 1) if causal else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].astype(jnp.float32)            # [bq, 1]
        delta = delta_ref[0].astype(jnp.float32)        # [bq, 1]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = q @ k_blk.T
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)  # masked entries: exp(-inf) = 0
        dp = do @ v_blk.T
        ds = p * (dp - delta)
        dq_scr[...] = dq_scr[...] + ds @ k_blk

    @pl.when(ki == n_k - 1)
    def _write():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, causal: bool,
                          scale: float, block_q: int, block_k: int,
                          n_q: int):
    ki = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # a Q block strictly above this K block's first row is fully masked
    run = ((qb + 1) * block_q - 1 >= ki * block_k) if causal else (qb >= 0)

    @pl.when(run)
    def _compute():
        k = k_ref[0].astype(jnp.float32)                # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)            # [bq, d]
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[0].astype(jnp.float32)        # [bq, 1]
        delta_blk = delta_ref[0].astype(jnp.float32)
        s = (q_blk * scale) @ k.T                       # [bq, bk]
        if causal:
            s = _causal_mask(s, qb, ki, block_q, block_k)
        p = jnp.exp(s - lse_blk)
        dv_scr[...] = dv_scr[...] + p.T @ do_blk
        dp = do_blk @ v.T
        ds = p * (dp - delta_blk)
        dk_scr[...] = dk_scr[...] + (ds.T @ q_blk) * scale

    @pl.when(qb == n_q - 1)
    def _write():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal: bool, scale: float,
                    block_q: int, block_k: int, interpret: bool,
                    g_lse=None):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    bq = _pick_block(block_q, t_q)
    bk = _pick_block(block_k, t_k)
    n_q, n_k = t_q // bq, t_k // bk

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)
    dof = g.reshape(b * h, t_q, d)
    of = o.reshape(b * h, t_q, d)
    # delta_i = sum_d dO_i O_i — O(T) rowwise, plain XLA; an lse cotangent
    # enters with opposite sign (dL/ds += g_lse * p)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.reshape(b * h, t_q).astype(jnp.float32)
    # trailing singleton keeps lse/delta sublane-major inside the kernels
    # (a [bq]-lane -> [bq, 1]-sublane reshape is a transpose Mosaic hates)
    lse3 = lse.reshape(b * h, t_q, 1)
    delta3 = delta.reshape(b * h, t_q, 1)

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, causal=causal,
                                  scale=scale, block_q=bq, block_k=bk,
                                  n_k=n_k)
    kv_map = _kv_index_map(causal, bq, bk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), kv_map),
            pl.BlockSpec((1, bk, d), kv_map),
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lse3, delta3)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                                   scale=scale, block_q=bq, block_k=bk,
                                   n_q=n_q)
    q_map = _q_index_map(causal, bq, bk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_map),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qb: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qb: (bh, ki, 0)),
            pl.BlockSpec((1, bq, d), q_map),
            pl.BlockSpec((1, bq, 1), q_map),
            pl.BlockSpec((1, bq, 1), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, ki, qb: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qb: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, dof, lse3, delta3)

    return (dq.reshape(b, h, t_q, d), dk.reshape(b, h, t_k, d),
            dv.reshape(b, h, t_k, d))


def estimate_vmem_bytes(t_q: int, t_k: int, d: int, block_q: int = 256,
                        block_k: int = 256) -> int:
    """Worst-case per-program VMEM residency across the three kernels
    (blocks + f32 scratch), double-buffered DMA included.  Sequence-length
    independent by construction — the long-context guarantee."""
    bq = _pick_block(block_q, t_q)
    bk = _pick_block(block_k, t_k)
    f32 = 4

    def dbl(*block_bytes):  # pallas double-buffers streamed blocks
        return 2 * sum(block_bytes)

    fwd = dbl(bq * d * f32, 2 * bk * d * f32, bq * d * f32, bq * f32) \
        + (bq * d + 2 * bq) * f32
    dq = dbl(bq * d * f32 * 2, 2 * bk * d * f32, 2 * bq * f32,
             bq * d * f32) + bq * d * f32
    dkv = dbl(bq * d * f32 * 2, 2 * bk * d * f32, 2 * bq * f32,
              2 * bk * d * f32) + 2 * bk * d * f32
    return max(fwd, dq, dkv)


def _reference_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        qi = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(ki <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_lse(q, k, v, causal: bool = True,
                        scale: Optional[float] = None, block_q: int = 256,
                        block_k: int = 256,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-row logsumexp
    [batch*heads, seq] (f32) — differentiable in BOTH outputs, which ring
    attention needs (the online merge weights blocks by their lse)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _fwd_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = flash_attention_lse(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
    return (out, lse), (q, k, v, out, lse)


def _bwd_lse(causal, scale, block_q, block_k, interpret, res, cts):
    q, k, v, o, lse = res
    g, g_lse = cts
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # dL/ds_ij = p_ij * (dp_ij - delta_i) + g_lse_i * p_ij: the lse
    # cotangent folds into delta (delta' = delta - g_lse), so the same
    # kernels serve both outputs
    return _flash_backward(q, k, v, o, lse, g, causal, scale, block_q,
                           block_k, interpret,
                           g_lse=None if g_lse is None else g_lse)


flash_attention_lse.defvjp(_fwd_lse, _bwd_lse)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: Optional[bool] = None):
    """q, k, v: [batch, heads, seq, head_dim].  Returns same shape.

    `interpret=None` auto-selects the Pallas interpreter off-TPU so tests
    run on CPU; on TPU the kernels compile natively.
    """
    out, _ = flash_attention_lse(q, k, v, causal, scale, block_q, block_k,
                                 interpret)
    return out


# ------------------------------------------------- single-query decode


def _flash_decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, o_scr, m_scr,
                         l_scr, *, scale: float, block_k: int, n_k: int):
    """One query row against a streamed K/V cache: the forward kernel with
    bq=1 and the causal mask replaced by a per-row length mask (cache
    positions >= length are unwritten slots, not future tokens)."""
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        o_scr[...] = jnp.zeros_like(o_scr)

    length = len_ref[0, 0]

    @pl.when(ki * block_k < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale        # [1, d]
        k_blk = k_ref[0].astype(jnp.float32)            # [bk, d]
        v_blk = v_ref[0].astype(jnp.float32)
        s = q @ k_blk.T                                 # [1, bk]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, _NEG_INF)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[...] = o_scr[...] * alpha + p @ v_blk

    @pl.when(ki == n_k - 1)
    def _write():
        o_ref[0] = (o_scr[...] / jnp.maximum(l_scr[...], 1e-30)).astype(
            o_ref.dtype)


def flash_decode_attention(q, k, v, lengths, scale: Optional[float] = None,
                           block_k: Optional[int] = None,
                           interpret: Optional[bool] = None):
    """Single-query flash attention against a KV cache (the decode step).

    q: [batch, heads, head_dim] — ONE query per sequence; k, v: [batch,
    heads, max_len, head_dim] cache buffers; lengths: int32 [batch] valid
    prefix length per row (positions >= length are masked).  Returns
    [batch, heads, head_dim].  VMEM residency is O(block_k), independent
    of the cache length.
    """
    from easydist_tpu import config as edconfig

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if block_k is None:
        block_k = edconfig.decode_block_k
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, d = q.shape
    t_k = k.shape[2]
    bk = _pick_block(block_k, t_k)
    n_k = t_k // bk

    qf = q.reshape(b * h, 1, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)
    # one scalar length per (b, h) row, SMEM-resident for the mask compare
    lenf = jnp.broadcast_to(
        lengths.astype(jnp.int32)[:, None], (b, h)).reshape(b * h, 1)

    kernel = functools.partial(_flash_decode_kernel, scale=scale,
                               block_k=bk, n_k=n_k)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_k),
        in_specs=[
            pl.BlockSpec((1, 1), lambda bh, ki: (bh, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda bh, ki: (bh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(lenf, qf, kf, vf)
    return out.reshape(b, h, d)


def _decode_attention_xla(q, k, v, lengths, scale: float):
    """Masked dot_general decode path — the off-TPU fallback, and the
    numerical reference the kernel is tested against.  Masking matches the
    models' einsum path (-1e30 fill, softmax over the full cache length)
    so cached and uncached greedy decode agree argmax-exactly."""
    s = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    s = jnp.where(k_pos < lengths.astype(jnp.int32)[:, None, None], s,
                  _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bhkd->bhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def decode_attention(q, k, v, lengths, scale: Optional[float] = None,
                     backend: Optional[str] = None):
    """Backend-dispatching decode attention (the models' decode steps call
    this): the Pallas single-query kernel on TPU, the masked dot_general
    path elsewhere.  `EASYDIST_DECODE_ATTENTION` forces either
    ("flash"/"xla"); the choice is part of the strategy-cache salt."""
    from easydist_tpu import config as edconfig

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.broadcast_to(lengths, (q.shape[0],))
    if backend is None:
        backend = edconfig.decode_attention_backend
    if backend == "paged":
        # "paged" selects the page-gathering kernel in paged_decode_attention;
        # contiguous callers degrade to auto (there is no table to chase)
        backend = "auto"
    if backend == "auto":
        backend = "flash" if jax.default_backend() == "tpu" else "xla"
    if backend == "flash":
        return flash_decode_attention(q, k, v, lengths, scale=scale)
    if backend == "xla":
        return _decode_attention_xla(q, k, v, lengths, scale)
    raise ValueError(f"unknown decode attention backend {backend!r}; "
                     f"expected auto|flash|xla|paged")


# ------------------------------------------------- paged decode


def gather_pages(pages, table, n_heads: Optional[int] = None):
    """Materialize the contiguous "virtual cache" a page table describes.

    pages: [n_pages, kv_heads, page_tokens, d] (one layer of the arena);
    table: int32 [batch, max_pages] arena page per window (sentinel
    `n_pages` for unmapped).  Returns [batch, heads, max_pages *
    page_tokens, d]: sentinel entries clip to the last real page, whose
    rows sit at masked positions (>= the row's length) so their softmax
    weight is exactly zero — garbage values are unobservable as long as
    they are finite, which arena zeros/stale KV always are.  `n_heads`
    repeats kv_heads GQA-style AFTER the gather, matching the bucketed
    llama path's repeat-then-attend order bitwise."""
    n_pages, kvh, pt, d = pages.shape
    b, mp = table.shape
    idx = jnp.clip(table.astype(jnp.int32), 0, n_pages - 1)
    v = jnp.take(pages, idx, axis=0)                 # [b, mp, kvh, pt, d]
    v = v.transpose(0, 2, 1, 3, 4).reshape(b, kvh, mp * pt, d)
    if n_heads is not None and n_heads != kvh:
        v = jnp.repeat(v, n_heads // kvh, axis=1)
    return v


def _paged_decode_attention_xla(q, k_pages, v_pages, table, lengths,
                                scale: float):
    """Gather-then-mask fallback: reconstruct the virtual contiguous cache
    through the page table, then run the exact `_decode_attention_xla`
    einsum.  When max_pages * page_tokens equals the bucketed cache
    length, every downstream shape (and therefore the lowered reduction
    order) matches the bucketed path — the bitwise-parity spine of the
    paged serving tests."""
    h = q.shape[1]
    kf = gather_pages(k_pages, table, n_heads=h)
    vf = gather_pages(v_pages, table, n_heads=h)
    return _decode_attention_xla(q, kf, vf, lengths, scale)


# ------------------------------------------- block-scaled int8 KV pages
#
# The EQuARX idiom (comm/quant.py) applied to KV pages: each K/V row is
# split into `n_blocks` equal head-dim blocks, every block carries one f32
# scale (amax / 127), and the payload is stored int8.  `jnp.rint` is
# round-half-to-even — deterministic, so re-prefilling the same token
# prefix reproduces quantized pages BITWISE (the crash-resume parity the
# int8 fleet-chaos wave gates).  Scales live in a parallel scale arena
# ({"k_scale", "v_scale"}: [..., page_tokens, n_blocks] f32) that rides
# the same page table indices as the payload.

_KV_QMAX = 127.0


def kv_quantize(x, n_blocks: int):
    """Block-scaled int8 over the LAST dim of `x` [..., d] with d split
    into `n_blocks` equal blocks.  Returns (q int8 [..., d],
    scales f32 [..., n_blocks]); all-zero blocks get scale 1.0 so
    dequantization is exact for them."""
    d = x.shape[-1]
    if d % n_blocks:
        raise ValueError(f"head_dim {d} not a multiple of n_blocks "
                         f"{n_blocks}")
    block = d // n_blocks
    xb = x.astype(jnp.float32).reshape(*x.shape[:-1], n_blocks, block)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / _KV_QMAX, 1.0)
    q = jnp.clip(jnp.rint(xb / scale), -_KV_QMAX, _KV_QMAX).astype(jnp.int8)
    return q.reshape(x.shape), scale[..., 0]


def kv_dequantize(q, scales, dtype=jnp.float32):
    """Inverse of `kv_quantize`: q int8 [..., d], scales f32
    [..., n_blocks] -> [..., d] in `dtype`."""
    d = q.shape[-1]
    nb = scales.shape[-1]
    block = d // nb
    xb = q.astype(jnp.float32).reshape(*q.shape[:-1], nb, block)
    return (xb * scales[..., None]).reshape(q.shape).astype(dtype)


def _paged_decode_attention_quant_xla(q, k_pages, v_pages, k_scale,
                                      v_scale, table, lengths,
                                      scale: float):
    """Quantized gather-then-mask fallback: gather int8 payload AND scale
    pages through the same table, dequantize to f32, then run the exact
    `_decode_attention_xla` einsum — the numerical reference the quant
    kernel is tested against."""
    h = q.shape[1]
    kf = kv_dequantize(gather_pages(k_pages, table, n_heads=h),
                       gather_pages(k_scale, table, n_heads=h))
    vf = kv_dequantize(gather_pages(v_pages, table, n_heads=h),
                       gather_pages(v_scale, table, n_heads=h))
    return _decode_attention_xla(q, kf, vf, lengths, scale)


def _flash_paged_decode_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref,
                               o_ref, o_scr, m_scr, l_scr, *, scale: float,
                               page_tokens: int, n_pages_max: int):
    """The single-query decode kernel with the K/V stream indirected
    through the page table: grid step pi wants the page holding tokens
    [pi*pt, (pi+1)*pt), and the BlockSpec index map (not the kernel body)
    resolves it via the scalar-prefetched table, so dead windows clamp to
    a repeated index and Pallas skips their DMA entirely."""
    bi = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        o_scr[...] = jnp.zeros_like(o_scr)

    length = len_ref[bi]

    @pl.when(pi * page_tokens < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale        # [1, d]
        k_blk = k_ref[0, 0].astype(jnp.float32)         # [pt, d]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = q @ k_blk.T                                 # [1, pt]
        k_pos = pi * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, _NEG_INF)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[...] = o_scr[...] * alpha + p @ v_blk

    @pl.when(pi == n_pages_max - 1)
    def _write():
        o_ref[0] = (o_scr[...] / jnp.maximum(l_scr[...], 1e-30)).astype(
            o_ref.dtype)


def flash_paged_decode_attention(q, k_pages, v_pages, table, lengths,
                                 scale: Optional[float] = None,
                                 interpret: Optional[bool] = None):
    """Single-query flash attention through a page table (paged decode).

    q: [batch, heads, head_dim]; k_pages/v_pages: [n_pages, kv_heads,
    page_tokens, head_dim] arena layers; table: int32 [batch, max_pages];
    lengths: int32 [batch].  The table and lengths ride
    `PrefetchScalarGridSpec` scalar prefetch: they land in SMEM before the
    grid runs, so the K/V BlockSpec index maps can chase the indirection
    and clamp dead windows (>= the row's live page count) to the last
    live page — a repeated index that Pallas serves without re-DMA, the
    paged extension of the contiguous kernel's dead-block skip.  GQA maps
    query head hi to kv head hi // (heads // kv_heads) in the same index
    maps.  Returns [batch, heads, head_dim]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, d = q.shape
    n_pages, kvh, pt, _ = k_pages.shape
    mp = table.shape[1]
    if h % kvh:
        raise ValueError(f"heads {h} not a multiple of kv_heads {kvh}")
    rep = h // kvh
    tbl = jnp.asarray(table, jnp.int32)
    lens = jnp.asarray(lengths, jnp.int32)

    def kv_map(bi, hi, pi, tbl_ref, len_ref):
        # dead windows (pi past the row's live pages) clamp to the last
        # live one: repeated index -> no DMA; @pl.when skips the compute
        last_live = jnp.maximum(
            jax.lax.div(len_ref[bi] + pt - 1, pt) - 1, 0)
        page = tbl_ref[bi, jnp.minimum(pi, last_live)]
        return (jnp.clip(page, 0, n_pages - 1), hi // rep, 0, 0)

    kernel = functools.partial(_flash_paged_decode_kernel, scale=scale,
                               page_tokens=pt, n_pages_max=mp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, mp),
        in_specs=[
            pl.BlockSpec((1, 1, d),
                         lambda bi, hi, pi, tbl_ref, len_ref: (bi, hi, 0)),
            pl.BlockSpec((1, 1, pt, d), kv_map),
            pl.BlockSpec((1, 1, pt, d), kv_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, d), lambda bi, hi, pi, tbl_ref, len_ref: (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tbl, lens, q, k_pages, v_pages)
    return out


def _flash_paged_decode_quant_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref,
                                     ks_ref, vs_ref, o_ref, o_scr, m_scr,
                                     l_scr, *, scale: float,
                                     page_tokens: int, n_pages_max: int):
    """`_flash_paged_decode_kernel` over block-scaled int8 pages: the K/V
    blocks arrive int8 with their per-block f32 scales riding the SAME
    page-table index map, and dequantization happens in VMEM inside the
    online-softmax loop — the arena stream stays int8 all the way from
    HBM, which is the whole 2-4x bytes/seq win."""
    bi = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        o_scr[...] = jnp.zeros_like(o_scr)

    length = len_ref[bi]

    @pl.when(pi * page_tokens < length)
    def _compute():
        pt, d = k_ref.shape[2], k_ref.shape[3]
        nb = ks_ref.shape[3]
        q = q_ref[0].astype(jnp.float32) * scale        # [1, d]

        def dq(blk_ref, s_ref):
            blk = blk_ref[0, 0].astype(jnp.float32)     # [pt, d]
            sc = s_ref[0, 0]                            # [pt, nb]
            if nb == 1:
                return blk * sc
            return (blk.reshape(pt, nb, d // nb)
                    * sc[:, :, None]).reshape(pt, d)

        k_blk = dq(k_ref, ks_ref)
        v_blk = dq(v_ref, vs_ref)
        s = q @ k_blk.T                                 # [1, pt]
        k_pos = pi * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, _NEG_INF)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_scr[...] = o_scr[...] * alpha + p @ v_blk

    @pl.when(pi == n_pages_max - 1)
    def _write():
        o_ref[0] = (o_scr[...] / jnp.maximum(l_scr[...], 1e-30)).astype(
            o_ref.dtype)


def flash_paged_decode_quant_attention(q, k_pages, v_pages, k_scale,
                                       v_scale, table, lengths,
                                       scale: Optional[float] = None,
                                       interpret: Optional[bool] = None):
    """`flash_paged_decode_attention` over a block-scaled int8 arena.

    k_pages/v_pages: int8 [n_pages, kv_heads, page_tokens, head_dim];
    k_scale/v_scale: f32 [n_pages, kv_heads, page_tokens, n_blocks].  The
    scale pages ride the same scalar-prefetched table index map as the
    payload (one indirection, four streams), and the kernel dequantizes
    on-chip inside the online-softmax loop.  Returns [batch, heads,
    head_dim] in q.dtype."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, d = q.shape
    n_pages, kvh, pt, _ = k_pages.shape
    nb = k_scale.shape[-1]
    mp = table.shape[1]
    if h % kvh:
        raise ValueError(f"heads {h} not a multiple of kv_heads {kvh}")
    rep = h // kvh
    tbl = jnp.asarray(table, jnp.int32)
    lens = jnp.asarray(lengths, jnp.int32)

    def kv_map(bi, hi, pi, tbl_ref, len_ref):
        last_live = jnp.maximum(
            jax.lax.div(len_ref[bi] + pt - 1, pt) - 1, 0)
        page = tbl_ref[bi, jnp.minimum(pi, last_live)]
        return (jnp.clip(page, 0, n_pages - 1), hi // rep, 0, 0)

    kernel = functools.partial(_flash_paged_decode_quant_kernel,
                               scale=scale, page_tokens=pt, n_pages_max=mp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, mp),
        in_specs=[
            pl.BlockSpec((1, 1, d),
                         lambda bi, hi, pi, tbl_ref, len_ref: (bi, hi, 0)),
            pl.BlockSpec((1, 1, pt, d), kv_map),
            pl.BlockSpec((1, 1, pt, d), kv_map),
            pl.BlockSpec((1, 1, pt, nb), kv_map),
            pl.BlockSpec((1, 1, pt, nb), kv_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, d), lambda bi, hi, pi, tbl_ref, len_ref: (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tbl, lens, q, k_pages, v_pages, k_scale, v_scale)
    return out


def paged_decode_attention(q, k_pages, v_pages, table, lengths,
                           scale: Optional[float] = None,
                           backend: Optional[str] = None,
                           k_scale=None, v_scale=None):
    """Backend-dispatching paged decode attention (the models' paged
    decode steps call this): the Pallas page-gathering kernel on TPU, the
    gather + masked dot_general path elsewhere.
    `EASYDIST_DECODE_ATTENTION` forces it — "paged"/"flash" pick the
    kernel, "xla" the gather fallback — and the value rides the same
    strategy-cache salt entry as the contiguous knob.  When
    `k_scale`/`v_scale` are given the pages are block-scaled int8 and
    both backends dequantize before the softmax (in-VMEM for the kernel,
    post-gather for the fallback)."""
    from easydist_tpu import config as edconfig

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    lengths = jnp.asarray(lengths, jnp.int32)
    if lengths.ndim == 0:
        lengths = jnp.broadcast_to(lengths, (q.shape[0],))
    if backend is None:
        backend = edconfig.decode_attention_backend
    if backend == "auto":
        backend = "paged" if jax.default_backend() == "tpu" else "xla"
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None:
        if backend in ("paged", "flash"):
            return flash_paged_decode_quant_attention(
                q, k_pages, v_pages, k_scale, v_scale, table, lengths,
                scale=scale)
        if backend == "xla":
            return _paged_decode_attention_quant_xla(
                q, k_pages, v_pages, k_scale, v_scale, table, lengths,
                scale)
        raise ValueError(
            f"unknown paged decode attention backend {backend!r}; "
            f"expected auto|paged|flash|xla")
    if backend in ("paged", "flash"):
        return flash_paged_decode_attention(q, k_pages, v_pages, table,
                                            lengths, scale=scale)
    if backend == "xla":
        return _paged_decode_attention_xla(q, k_pages, v_pages, table,
                                           lengths, scale)
    raise ValueError(f"unknown paged decode attention backend {backend!r}; "
                     f"expected auto|paged|flash|xla")


def _chunk_attention_xla(q, k, v, q_pos, scale: float):
    """Masked dot_general chunked-prefill path: q [b, h, c, hd] at absolute
    positions `q_pos` (int32 [b, c]) attends the full cache window k/v
    [b, h, T, hd].  A key at position kp is visible iff kp <= q_pos, which
    is simultaneously the causal mask *within* the chunk and the validity
    mask over the cache tail (stale rows beyond the row's live length sit
    at positions > q_pos, so their softmax weight underflows to exact 0 —
    the no-stale-leakage property SERVE002 audits statically)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
    s = jnp.where(k_pos <= q_pos.astype(jnp.int32)[:, None, :, None], s,
                  _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def chunk_attention(q, k, v, q_pos, scale: Optional[float] = None,
                    backend: Optional[str] = None):
    """Backend-dispatching chunked-prefill attention (the models'
    `*_prefill_chunk` call this): q is a fixed-size token chunk at
    absolute positions `q_pos`, k/v are the full bucket-length cache.
    `EASYDIST_PREFILL_ATTENTION` forces the backend; today both "auto"
    and "xla" resolve to the masked dot_general path (a blocked Pallas
    variant can slot in behind the same knob), and the choice is part of
    the strategy-cache salt like the decode backend."""
    from easydist_tpu import config as edconfig

    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if backend is None:
        backend = edconfig.prefill_attention_backend
    if backend in ("auto", "xla"):
        return _chunk_attention_xla(q, k, v, q_pos, scale)
    raise ValueError(f"unknown prefill attention backend {backend!r}; "
                     f"expected auto|xla")
