"""Flash attention as Pallas TPU kernels (forward AND fused backward).

Blockwise online-softmax attention: Q blocks stream over the grid, K/V live
in VMEM per (batch*head) program, statistics (running max / denominator)
stay in f32 scratch.  O(seq) memory instead of materializing the [T, T]
score matrix; MXU-shaped matmul blocks.

The backward is the FlashAttention-2 recipe: the forward saves the per-row
logsumexp, `delta = rowsum(dO * O)` is precomputed, then two kernels stream
blocks — dQ over Q-blocks (K/V resident), dK/dV over K-blocks (Q/dO
resident) — recomputing P = exp(S - lse) per block.  No [T, T] residual
survives the forward.  The ring variant composes this kernel with the
ppermute loop in parallel/ring_attention.py.

Reference scenario: the reference relies on torch SDPA/cutlass kernels
(benchmark/torch/model/gpt.py attention); this is the TPU-native analog.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  seq_k: int, causal: bool, scale: float, q_block: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
    bq, d = q.shape

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)

    q_pos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, carry):
        o_acc, m_acc, l_acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T  # [bq, block_k]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1)
        o_new = o_acc * alpha[:, None] + p @ v_blk
        return o_new, m_new, l_new

    n_kb = seq_k // block_k
    if causal:
        # blocks fully above the diagonal contribute nothing; bound the loop
        # at the q block's last row
        n_kb_eff = jnp.minimum(n_kb, (qi + 1) * q_block // block_k
                               + (1 if q_block % block_k else 0))
    else:
        n_kb_eff = n_kb
    o_acc, m_acc, l_acc = jax.lax.fori_loop(0, n_kb_eff, body, (o0, m0, l0))
    l_safe = jnp.maximum(l_acc, 1e-30)
    o_ref[0] = (o_acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m_acc + jnp.log(l_safe)).astype(jnp.float32)


def _pick_block(block: int, t: int) -> int:
    b = min(block, t)
    while t % b:
        b //= 2
    return max(b, 1)


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    bq = _pick_block(block_q, t_q)
    bk = _pick_block(block_k, t_k)

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)

    kernel = functools.partial(_flash_kernel, block_k=bk, seq_k=t_k,
                               causal=causal, scale=scale, q_block=bq)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq), lambda bh, qi: (bh, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t_q), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t_q, d), lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, seq_k: int, causal: bool,
                         scale: float, q_block: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0].astype(jnp.float32)
    delta = delta_ref[0].astype(jnp.float32)
    bq, d = q.shape
    q_pos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, dq_acc):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])  # masked entries: exp(-inf) = 0
        dp = do @ v_blk.T
        ds = p * (dp - delta[:, None])
        return dq_acc + ds @ k_blk

    n_kb = seq_k // block_k
    if causal:
        n_kb_eff = jnp.minimum(n_kb, (qi + 1) * q_block // block_k
                               + (1 if q_block % block_k else 0))
    else:
        n_kb_eff = n_kb
    dq0 = jnp.zeros((bq, d), jnp.float32)
    dq_acc = jax.lax.fori_loop(0, n_kb_eff, body, dq0)
    dq_ref[0] = (dq_acc * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, seq_q: int,
                          causal: bool, scale: float, k_block: int):
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    bk, d = k.shape
    k_pos = ki * k_block + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def body(qb, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, pl.ds(qb * block_q, block_q)].astype(jnp.float32)
        delta_blk = delta_ref[0, pl.ds(qb * block_q, block_q)] \
            .astype(jnp.float32)
        s = (q_blk * scale) @ k.T  # [block_q, bk]
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])
        dv_acc = dv_acc + p.T @ do_blk
        dp = do_blk @ v.T
        ds = p * (dp - delta_blk[:, None])
        dk_acc = dk_acc + (ds.T @ q_blk) * scale
        return dk_acc, dv_acc

    n_qb = seq_q // block_q
    if causal:
        # q blocks strictly above this k block's first row are fully masked
        start = (ki * k_block) // block_q
    else:
        start = 0
    zeros = jnp.zeros((bk, d), jnp.float32)
    dk_acc, dv_acc = jax.lax.fori_loop(start, n_qb, body, (zeros, zeros))
    dk_ref[0] = dk_acc.astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal: bool, scale: float,
                    block_q: int, block_k: int, interpret: bool,
                    g_lse=None):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    bq = _pick_block(block_q, t_q)
    bk = _pick_block(block_k, t_k)

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)
    dof = g.reshape(b * h, t_q, d)
    of = o.reshape(b * h, t_q, d)
    # delta_i = sum_d dO_i O_i — O(T) rowwise, plain XLA; an lse cotangent
    # enters with opposite sign (dL/ds += g_lse * p)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.reshape(b * h, t_q).astype(jnp.float32)

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, block_k=bk,
                                  seq_k=t_k, causal=causal, scale=scale,
                                  q_block=bq)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, t_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq), lambda bh, qi: (bh, qi)),
            pl.BlockSpec((1, bq), lambda bh, qi: (bh, qi)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, block_q=bq,
                                   seq_q=t_q, causal=causal, scale=scale,
                                   k_block=bk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, t_k // bk),
        in_specs=[
            pl.BlockSpec((1, t_q, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, t_q, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, t_q), lambda bh, ki: (bh, 0)),
            pl.BlockSpec((1, t_q), lambda bh, ki: (bh, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_k, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return (dq.reshape(b, h, t_q, d), dk.reshape(b, h, t_k, d),
            dv.reshape(b, h, t_k, d))


def _reference_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        qi = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(ki <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_lse(q, k, v, causal: bool = True,
                        scale: Optional[float] = None, block_q: int = 256,
                        block_k: int = 256,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-row logsumexp
    [batch*heads, seq] (f32) — differentiable in BOTH outputs, which ring
    attention needs (the online merge weights blocks by their lse)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _fwd_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = flash_attention_lse(q, k, v, causal, scale, block_q, block_k,
                                   interpret)
    return (out, lse), (q, k, v, out, lse)


def _bwd_lse(causal, scale, block_q, block_k, interpret, res, cts):
    q, k, v, o, lse = res
    g, g_lse = cts
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # dL/ds_ij = p_ij * (dp_ij - delta_i) + g_lse_i * p_ij: the lse
    # cotangent folds into delta (delta' = delta - g_lse), so the same
    # kernels serve both outputs
    return _flash_backward(q, k, v, o, lse, g, causal, scale, block_q,
                           block_k, interpret,
                           g_lse=None if g_lse is None else g_lse)


flash_attention_lse.defvjp(_fwd_lse, _bwd_lse)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: Optional[bool] = None):
    """q, k, v: [batch, heads, seq, head_dim].  Returns same shape.

    `interpret=None` auto-selects the Pallas interpreter off-TPU so tests
    run on CPU; on TPU the kernels compile natively.
    """
    out, _ = flash_attention_lse(q, k, v, causal, scale, block_q, block_k,
                                 interpret)
    return out
