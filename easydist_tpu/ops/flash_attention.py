"""Flash attention as a Pallas TPU kernel.

Blockwise online-softmax attention: Q blocks stream over the grid, K/V live
in VMEM per (batch*head) program, statistics (running max / denominator)
stay in f32 scratch.  O(seq) memory instead of materializing the [T, T]
score matrix; MXU-shaped matmul blocks.

The backward pass recomputes attention in plain jax (correct, O(T^2) bytes
in the bwd only); a fused flash backward kernel is future work.  The ring
variant composes this kernel with the ppermute loop in
parallel/ring_attention.py.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_k: int,
                  causal: bool, scale: float, q_block: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [bq, d]
    bq, d = q.shape

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    o0 = jnp.zeros((bq, d), jnp.float32)

    q_pos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(kb, carry):
        o_acc, m_acc, l_acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_blk.T  # [bq, block_k]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_acc - m_new)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1)
        o_new = o_acc * alpha[:, None] + p @ v_blk
        return o_new, m_new, l_new

    n_kb = seq_k // block_k
    if causal:
        # blocks fully above the diagonal contribute nothing; bound the loop
        # at the q block's last row
        n_kb_eff = jnp.minimum(n_kb, (qi + 1) * q_block // block_k
                               + (1 if q_block % block_k else 0))
    else:
        n_kb_eff = n_kb
    o_acc, m_acc, l_acc = jax.lax.fori_loop(0, n_kb_eff, body, (o0, m0, l0))
    o_ref[0] = (o_acc / jnp.maximum(l_acc, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    bq = min(block_q, t_q)
    bk = min(block_k, t_k)
    while t_q % bq:
        bq //= 2
    while t_k % bk:
        bk //= 2
    bq, bk = max(bq, 1), max(bk, 1)

    qf = q.reshape(b * h, t_q, d)
    kf = k.reshape(b * h, t_k, d)
    vf = v.reshape(b * h, t_k, d)

    kernel = functools.partial(_flash_kernel, block_k=bk, seq_k=t_k,
                               causal=causal, scale=scale, q_block=bq)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t_q, d)


def _reference_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        qi = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(ki <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: Optional[bool] = None):
    """q, k, v: [batch, heads, seq, head_dim].  Returns same shape.

    `interpret=None` auto-selects the Pallas interpreter off-TPU so tests
    run on CPU; on TPU the kernel compiles natively.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    def ref(q, k, v):
        return _reference_attention(q, k, v, causal, scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
