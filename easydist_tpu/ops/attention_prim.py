"""Solver-visible attention composite (SURVEY §7 step 7; VERDICT r3 #3).

`attention(q, k, v)` traces to a pair of first-class jax primitives —
`ed_attention_fwd` / `ed_attention_bwd` (glued by jax.custom_vjp, so the
differentiated jaxpr contains both as plain equations after inlining).
Each primitive carries EXPLICIT sharding strategies for the auto-parallel
solver (jaxfront/presets.py):

  batch  S(0)->S(0)            free
  head   S(1)->S(1)            free   (megatron TP)
  seq    S(2)->S(2)            intrinsic cost = ring ppermute bytes or
                               Ulysses all_to_all bytes, whichever is
                               cheaper at this world size; the chosen
                               variant rides NodeStrategy.meta

When the solver picks the seq strategy, emission
(jaxfront/api.py::emit_sharded_fn) lowers the equation to the REAL
ring/Ulysses program (parallel/ring_attention.py, parallel/ulysses.py)
instead of binding the primitive — O(t/n) attention memory, collectives on
the wire exactly as priced.  The backward equation is emitted as the vjp of
the same program (flash-style recompute: no [t,t] residual ever exists).

The mechanism this matches in the reference is the preset-rule bank
(easydist/torch/preset_propagation.py:32-57); the reference has no
attention-level rule at all — sdpa shards only via DTensor's per-op rules
(easydist/torch/spmd_prop_rule.py), and no sequence-parallel variant exists
there (SURVEY §2.9).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.extend import core as jex_core
from jax.interpreters import mlir

__all__ = ["attention", "ed_attention_fwd_p", "ed_attention_bwd_p",
           "seq_strategy_costs"]


# ------------------------------------------------------------ reference math

def _einsum_attention(q, k, v, causal: bool, scale: float):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t_q, t_k = q.shape[2], k.shape[2]
        qi = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(ki <= qi, s, jnp.array(-1e30, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ----------------------------------------------------------------- primitives

ed_attention_fwd_p = jex_core.Primitive("ed_attention_fwd")
ed_attention_bwd_p = jex_core.Primitive("ed_attention_bwd")
ed_attention_bwd_p.multiple_results = True


def _fwd_impl(q, k, v, *, causal, scale):
    return _einsum_attention(q, k, v, causal, scale)


def _bwd_impl(q, k, v, dout, *, causal, scale):
    # recompute-based backward: the residual is (q, k, v), never the [t,t]
    # probability matrix — the property that makes long-context training fit
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _einsum_attention(q_, k_, v_, causal, scale),
        q, k, v)
    return list(vjp(dout))


ed_attention_fwd_p.def_impl(_fwd_impl)
ed_attention_bwd_p.def_impl(_bwd_impl)


@ed_attention_fwd_p.def_abstract_eval
def _fwd_abstract(q, k, v, *, causal, scale):
    from jax.core import ShapedArray

    return ShapedArray(q.shape, q.dtype)


@ed_attention_bwd_p.def_abstract_eval
def _bwd_abstract(q, k, v, dout, *, causal, scale):
    from jax.core import ShapedArray

    return [ShapedArray(a.shape, a.dtype) for a in (q, k, v)]


mlir.register_lowering(ed_attention_fwd_p,
                       mlir.lower_fun(_fwd_impl, multiple_results=False))
mlir.register_lowering(ed_attention_bwd_p,
                       mlir.lower_fun(_bwd_impl, multiple_results=True))


# ----------------------------------------------------------------- public api

def attention(q, k, v, causal: bool = True, scale: Optional[float] = None):
    """Multi-head attention the auto-parallel solver can see through.

    q, k, v: [batch, heads, seq, head_dim].  Differentiable (custom_vjp:
    the backward is its own solver-visible primitive).  Outside
    `easydist_compile`, evaluates as plain einsum attention.
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    return _attention_cv(q, k, v, bool(causal), float(scale))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _attention_cv(q, k, v, causal, scale):
    return ed_attention_fwd_p.bind(q, k, v, causal=causal, scale=scale)


def _attention_fwd_rule(q, k, v, causal, scale):
    return _attention_cv(q, k, v, causal, scale), (q, k, v)


def _attention_bwd_rule(causal, scale, res, dout):
    q, k, v = res
    return tuple(ed_attention_bwd_p.bind(q, k, v, dout, causal=causal,
                                         scale=scale))


_attention_cv.defvjp(_attention_fwd_rule, _attention_bwd_rule)


# ------------------------------------------------------------- cost estimates

def seq_strategy_costs(q_shape, dtype_bytes: int, n: int, backward: bool):
    """(ring_seconds, ulysses_seconds) per step for seq-sharding attention
    over an n-device ICI axis — the intrinsic prices the solver weighs.

    Ring: K and V (each local t/n slice) rotate n-1 hops -> per-device wire
    bytes = 2 * (n-1)/n * kv_bytes; backward also rotates dK/dV (2x).
    Ulysses: all_to_all on q, k, v in and out back (4 tensors), each
    (n-1)/n^2 of global bytes; backward moves the same set again for the
    gradient all_to_alls.
    """
    from easydist_tpu import config as edconfig

    b, h, t, d = q_shape
    tensor_bytes = b * h * t * d * dtype_bytes
    bw = edconfig.ici_bandwidth
    lat = edconfig.ici_latency
    mult = 2.0 if backward else 1.0

    ring_bytes = 2.0 * (n - 1) / n * tensor_bytes * mult
    ring = ring_bytes / bw + (n - 1) * lat * (2 if backward else 1)

    punish = edconfig.all_to_all_punish_factor if n > 2 else 1.0
    ua_bytes = 4.0 * (n - 1) / (n * n) * tensor_bytes * punish * mult
    ulysses = ua_bytes / bw + 4 * lat * mult
    return ring, ulysses
