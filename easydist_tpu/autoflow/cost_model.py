"""Collective cost model parameterized by mesh-axis topology.

The reference's closed forms (autoflow/solver.py:49-56) assume one flat
device count; on TPU each mesh axis has its own interconnect — ICI rings
within a slice, DCN across slices — so costs here are seconds-on-wire:
bytes-transferred(collective, axis size) / axis bandwidth.  The solver only
compares costs, but using real bandwidths makes hybrid ICIxDCN meshes pick
the right axis for the heavy collectives.
"""

from __future__ import annotations

from dataclasses import dataclass

from easydist_tpu import config as edconfig
from easydist_tpu.metashard.metair import Placement


@dataclass
class MeshAxisSpec:
    """One axis of the device mesh as the solver sees it.

    bandwidth/latency keep their sentinel until READ (resolved_*): meshes
    are usually built before runtime calibration updates the config, so
    latching config values at construction would silently discard measured
    constants (runtime/calibrate.py)."""

    name: str
    size: int
    bandwidth: float = 0.0  # bytes/s; 0 -> per-kind config value at use
    kind: str = "ici"  # "ici" | "dcn"
    latency: float = -1.0  # seconds/launch; <0 -> per-kind config at use

    def resolved_bandwidth(self) -> float:
        if self.bandwidth > 0.0:
            return self.bandwidth
        return (edconfig.dcn_bandwidth if self.kind == "dcn"
                else edconfig.ici_bandwidth)

    def resolved_latency(self) -> float:
        if self.latency >= 0.0:
            return self.latency
        return (edconfig.dcn_latency if self.kind == "dcn"
                else edconfig.ici_latency)


def _all_gather(x: float, n: int) -> float:
    return x * (n - 1) / n


def _all_reduce(x: float, n: int) -> float:
    return 2 * x * (n - 1) / n


def _reduce_scatter(x: float, n: int) -> float:
    return x * (n - 1) / n


def _all_to_all(x: float, n: int) -> float:
    factor = edconfig.all_to_all_punish_factor if n > 2 else 1.0
    return factor * x * (n - 1) / (n * n)


def overlap_ratio_is_measured() -> bool:
    """True when a runtime-measured overlap fraction is available for this
    backend (runtime.calibrate.calibrate_overlap ran, or apply_calibration
    loaded one from the PerfDB)."""
    return edconfig.comm_overlap_ratio_measured is not None


def overlap_discount_ratio() -> float:
    """The comm/compute overlap fraction the solver may discount
    reduction-edge costs by, resolved per `comm_overlap_ratio_source`:

      "auto"      the MEASURED fraction when one exists for this backend,
                  else the configured `comm_overlap_ratio` guess;
      "measured"  only a measured fraction — 0.0 (discount off) until
                  `runtime.calibrate.calibrate_overlap` has run, so an
                  uncalibrated compile never trades real bytes for
                  imagined overlap;
      "config"    always the configured `comm_overlap_ratio` (the
                  reference's flat-guess behavior).
    """
    source = (edconfig.comm_overlap_ratio_source or "auto").lower()
    measured = edconfig.comm_overlap_ratio_measured
    if source == "config":
        ratio = edconfig.comm_overlap_ratio
    elif source == "measured":
        ratio = measured if measured is not None else 0.0
    else:  # "auto"
        ratio = measured if measured is not None \
            else edconfig.comm_overlap_ratio
    return float(min(max(ratio, 0.0), 1.0))


def comm_compression_ratio() -> float:
    """Wire-bytes ratio of the configured gradient-collective compression
    (easydist_tpu.comm): 1.0 when off, 0.5 for bf16, ~0.26 for int8
    (payload + one f32 scale per `comm_quant_block` elements)."""
    mode = (edconfig.comm_quant_dtype or "none").lower()
    if mode == "bf16":
        return 0.5
    if mode == "int8":
        block = max(edconfig.comm_quant_block, 1)
        return (1.0 + 4.0 / block) / 4.0
    return 1.0


def quantize_compute_cost(var_bytes: float) -> float:
    """Seconds of quantize/dequantize compute a compressed reduction pays:
    block-amax + scale + round + dequant is a handful of memory-bound
    passes over the buffer — priced as 4 HBM round-trips."""
    return 4.0 * var_bytes / edconfig.hbm_bandwidth


def resharding_cost(var_bytes: float, up: Placement, down: Placement,
                    axis: MeshAxisSpec) -> float:
    """Seconds to reshard one tensor from `up` to `down` along `axis`.

    `up` is what the producer emits, `down` what the consumer needs.
    Replicate -> anything is free (slicing is local); the collective cases
    mirror reference solver.py:58-72 plus the reduce_scatter case it lacks.

    When gradient-collective compression is enabled (`comm_quant_dtype`),
    the REDUCTION edges (P -> R all_reduce, P -> S reduce_scatter — the
    shapes the comm layer's quantized fences actually emit) are priced at
    min(exact, compressed): wire bytes scaled by the compression ratio
    plus the quantize-compute passes.  The ILP then defers/compresses only
    where the byte saving beats the quantize cost — exactly the
    solver-priced-compression contract of docs/COMM.md.
    """
    n = axis.size
    if n <= 1:
        return 0.0

    reduction_edge = False
    if up.is_shard():
        if down.is_shard():
            bytes_wire = 0.0 if up.dim == down.dim else _all_to_all(var_bytes, n)
        else:  # S -> R (or consumer tolerating partial): all_gather
            bytes_wire = _all_gather(var_bytes, n)
    elif up.is_partial():
        if down.is_shard():
            bytes_wire = _reduce_scatter(var_bytes, n)
            reduction_edge = True
        elif down.is_partial():
            bytes_wire = 0.0
        else:  # P -> R
            bytes_wire = _all_reduce(var_bytes, n)
            reduction_edge = True
    else:  # R -> anything is a local slice / no-op
        bytes_wire = 0.0

    if bytes_wire == 0.0:
        return 0.0
    # alpha-beta model: a collective pays a fixed launch/synchronization
    # latency on top of wire time.  Without the alpha term, sharding a tiny
    # bias is bytes-equal to replicating it (reduce_scatter + all_gather ==
    # all_reduce) and the memory tie-break scatters small params across the
    # mesh, emitting dozens of sub-KB collectives that cost pure latency.
    cost = axis.resolved_latency() + bytes_wire / axis.resolved_bandwidth()
    if reduction_edge and var_bytes >= 4.0 * edconfig.comm_quant_min_numel:
        ratio = comm_compression_ratio()
        if ratio < 1.0:
            compressed = (axis.resolved_latency()
                          + bytes_wire * ratio / axis.resolved_bandwidth()
                          + quantize_compute_cost(var_bytes))
            cost = min(cost, compressed)
    return cost


def collective_wire_bytes(kind: str, var_bytes: float, n: int) -> float:
    """Wire bytes of one collective family over `n` participants — the
    closed forms above, keyed by the kind labels a `reshard` plan's
    ChunkOps carry.  "local"/"slice" move nothing; unknown kinds price
    as a full point-to-point copy (pessimistic, never free)."""
    if n <= 1 or kind in ("local", "slice"):
        return 0.0
    if kind == "all_gather":
        return _all_gather(var_bytes, n)
    if kind == "all_reduce":
        return _all_reduce(var_bytes, n)
    if kind == "reduce_scatter":
        return _reduce_scatter(var_bytes, n)
    if kind == "all_to_all":
        return _all_to_all(var_bytes, n)
    return var_bytes


def redistribution_cost(wire_bytes: float, n_chunks: int,
                        axis: MeshAxisSpec) -> float:
    """Alpha-beta seconds of a chunked redistribution plan along `axis`:
    every chunk that moves bytes pays one collective launch latency on
    top of its share of the wire time (the same model `resharding_cost`
    applies to solver edges — a reshard plan is just N of those edges,
    so the solver and the elastic path price redistribution with one
    vocabulary)."""
    if wire_bytes <= 0.0:
        return 0.0
    return (max(1, n_chunks) * axis.resolved_latency()
            + wire_bytes / axis.resolved_bandwidth())


def placement_bytes(var_bytes: float, p: Placement, axis_size: int) -> float:
    """Per-device bytes held for a tensor under placement `p`."""
    if p is not None and p.is_shard():
        return var_bytes / axis_size
    return var_bytes
