"""Global SPMD strategy selection: binary ILP over cluster strategies.

Formulation (reference AutoFlowSolver1D, autoflow/solver.py:224-730, rebuilt
on scipy/HiGHS since neither `mip` nor `ortools` ships here):

  variables   y[c,s] in {0,1}   cluster c uses strategy s
              z[e,i,j] >= 0     edge e joins producer strategy i / consumer j
  constraints sum_s y[c,s] == 1
              z[e,i,j] >= y[up(e),i] + y[down(e),j] - 1
  objective   min sum_e C_e[i,j] z[e,i,j]  +  w_mem * sum_e M_e[i,j] z[e,i,j]

With one-hot y and non-negative costs the z lower bounds make z behave as the
product y_up*y_down at the optimum, so z stays continuous — the model has far
fewer integers than the reference's all-binary AND-linearization.

Optionally a hard per-device memory cap is enforced per liveness step
(the reference left this half-finished: solver.py:665-707 commented out).

An ND mesh is solved one axis at a time by the frontend (reference
compile_auto.py:128-173): strategies already chosen on earlier axes are
excluded from pools and shapes pre-shrunk before the next 1D solve.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
from scipy import sparse
from scipy.optimize import Bounds, LinearConstraint, milp

from easydist_tpu import config as edconfig
from easydist_tpu.metashard.metair import (MetaGraph, NodeStrategy,
                                          Placement)
from .cost_model import (MeshAxisSpec, overlap_discount_ratio,
                         placement_bytes, resharding_cost)

logger = logging.getLogger(__name__)

_op_times_cache: Optional[Tuple[Tuple[str, float], Dict[str, float]]] = None
# check-then-reload below is a read-mutate race under ServeEngine's
# concurrent bucket compiles (two threads can interleave the None check and
# the assignment, one returning a half-installed table); all access to the
# module global goes through this lock
_op_times_lock = threading.Lock()


def _cached_op_times() -> Dict[str, float]:
    """PerfDB op-time table, reloaded only when the DB file changes (the
    solver runs once per mesh axis per compile).  Thread-safe."""
    global _op_times_cache
    from easydist_tpu.runtime.perfdb import db_mtime

    path = edconfig.prof_db_path
    mtime = db_mtime(path)
    if mtime is None:
        return {}
    key = (path, mtime)
    with _op_times_lock:
        if _op_times_cache is None or _op_times_cache[0] != key:
            from easydist_tpu.runtime.op_profile import load_op_times

            _op_times_cache = (key, load_op_times())
        return _op_times_cache[1]


class _Edge:
    """One producer-cluster -> consumer-cluster tensor dependency."""

    def __init__(self, var, up_cluster, up_node, out_idx,
                 down_cluster, down_node, in_idx):
        self.var = var
        self.up_cluster = up_cluster
        self.up_node = up_node
        self.out_idx = out_idx
        self.down_cluster = down_cluster
        self.down_node = down_node
        self.in_idx = in_idx
        self.comm: Optional[np.ndarray] = None
        self.mem: Optional[np.ndarray] = None
        self.z_offset: int = -1

    def up_placement(self, i: int) -> Placement:
        s = self.up_cluster.strategies[i][self.up_node.uid][1]
        return s.out_placements[self.out_idx]

    def down_placement(self, j: int) -> Placement:
        s = self.down_cluster.strategies[j][self.down_node.uid][1]
        if self.down_node.is_input:
            # state_io edge: the placeholder's "need" is its own out placement
            return s.out_placements[self.in_idx]
        return s.in_placements[self.in_idx]


class SpmdSolver:
    """Solve one mesh axis for a coarsened MetaGraph."""

    def __init__(self, graph: MetaGraph, axis: MeshAxisSpec,
                 reachability=None, free_outputs: bool = False,
                 cluster_dedup: Optional[bool] = None):
        self.graph = graph
        self.axis = axis
        self.reachability = reachability
        # per-solve override of edconfig.solver_cluster_dedup (composite-body
        # solves pass False: tying would fight their per-placeholder pins)
        self.cluster_dedup = edconfig.solver_cluster_dedup \
            if cluster_dedup is None else cluster_dedup
        # composite-body solves (scan/remat): graph outputs cross the
        # composite boundary with their own recombines, so sharded/partial
        # outputs must not be priced as if handed back replicated
        self.free_outputs = free_outputs
        self.clusters = graph.clusters
        self.edges: List[_Edge] = []
        # pure edge-communication cost of the solution this solver last
        # returned, computed from its own pick indices — the analyzer's
        # objective audit (analyze.audit_solver_objective) recomputes the
        # same number independently via assignment_comm_cost and compares
        self.last_comm_cost: Optional[float] = None
        self._collect_edges()
        self._build_matrices()
        # isomorphic-cluster tying: identical transformer layers share one
        # set of ILP variables (reference pain point: per-cluster binaries,
        # autoflow/solver.py:266-273 — an L-layer stack solved L times over)
        self.tie_rep: Dict[int, int] = {c.cid: c.cid for c in self.clusters}
        # under a hard memory cap, only non-uniform per-instance assignments
        # may be feasible and refinement is disabled — solve untied
        if self.cluster_dedup and edconfig.per_device_memory_cap <= 0:
            self._compute_tie_groups()

    # ------------------------------------------------------------ model build

    def _collect_edges(self):
        by_cid = {c.cid: c for c in self.clusters}
        for node in self.graph.all_nodes():
            down_c = by_cid[node.cluster_id]
            for in_idx, var in enumerate(node.invars):
                if var is None or var.producer is None:
                    continue
                up = var.producer
                if up.cluster_id == node.cluster_id:
                    continue  # intra-cluster: sync-free by construction
                self.edges.append(_Edge(var, by_cid[up.cluster_id], up,
                                        var.producer_idx, down_c, node, in_idx))
        # state threading: the producer of an updated state tensor should land
        # on the same placement the matching input placeholder chose, else the
        # next step pays a reshard (reference state_io_map edges,
        # solver.py:279-283)
        for out_name, placeholder in self.graph.state_io.items():
            var = next((v for v in self.graph.outputs if v.name == out_name), None)
            if var is None or var.producer is None:
                continue
            self.edges.append(_Edge(var, by_cid[var.producer.cluster_id],
                                    var.producer, var.producer_idx,
                                    by_cid[placeholder.cluster_id], placeholder,
                                    0))

        # non-state graph outputs are handed back to the user replicated, so a
        # PARTIAL or SHARD producer pays the final collective here (reference
        # forces returns to REPLICATE, torch/passes/sharding.py:920-949).
        # Linear cost on the producer cluster's y variables.  The same
        # vector carries the compute-redundancy cost: a strategy that
        # replicates an op's outputs runs the op full-size on every device,
        # while sharded/partial outputs split the work 1/n — without this
        # term, replicate-everything is a free zero-communication optimum.
        self.output_y_cost: Dict[int, np.ndarray] = {}
        inv_hbm = 1.0 / edconfig.hbm_bandwidth
        # measured per-op seconds (PerfDB, keyed by the node's signature)
        # price compute-redundancy exactly; the HBM proxy covers misses
        # (reference runtime_prof.py:35-150 -> solver costs)
        op_times = _cached_op_times() if edconfig.use_op_cost_db else {}
        n_comp = n_hit = 0
        # strategy-independent per-node numbers, computed once (the cost
        # loop runs per cluster x strategy x node and dominates solve prep)
        from .reachability import _node_flops

        _node_cache: Dict[int, tuple] = {}

        def node_numbers(node):
            got = _node_cache.get(id(node))
            if got is None:
                got = (_node_flops(node),
                       [v.size_bytes() if v is not None else 0
                        for v in node.invars],
                       [v.size_bytes() if v is not None else 0
                        for v in node.outvars])
                _node_cache[id(node)] = got
            return got

        for c in self.clusters:
            costs = None
            for s in range(c.strategy_count()):
                t = 0.0
                for uid, (_, strat) in c.strategies[s].items():
                    node = c.nodes[uid]
                    if node.is_input:
                        continue
                    measured = op_times.get(node.sig) if node.sig else None
                    if s == 0:
                        n_comp += 1
                        n_hit += measured is not None
                    strat_compute = getattr(strat, "compute_cost", None)
                    if strat_compute is not None:
                        # composite strategies price their body per-op
                        t += strat_compute
                    elif measured is not None or \
                            node.compute_proxy is not None:
                        full_t = measured if measured is not None \
                            else node.compute_proxy
                        # scalar time sources: only SHARD splits the work
                        # 1/n (a pure P-propagating op runs full-shape on
                        # every rank, same as replicate)
                        sharded = any(
                            p is not None and p.is_shard()
                            for p in list(strat.out_placements)
                            + list(strat.in_placements))
                        factor = (1.0 / self.axis.size) if sharded else 1.0
                        t += factor * full_t
                    else:
                        n = self.axis.size
                        flops, in_b, out_b = node_numbers(node)
                        sharded = any(
                            p is not None and p.is_shard()
                            for p in list(strat.out_placements)
                            + list(strat.in_placements))
                        if flops > 0.0:
                            # MXU ops: per-strategy roofline at LOCAL
                            # sizes, discounting only the vars the
                            # strategy actually shards.  This is what
                            # makes weight-stationary TP visible — an
                            # output-bytes proxy hides the weight-read
                            # half of its savings (r5 Phase B).
                            nbytes = sum(
                                b / n if (p is not None and p.is_shard())
                                else b for b, p in
                                zip(in_b, strat.in_placements))
                            nbytes += sum(
                                b / n if (p is not None and p.is_shard())
                                else b for b, p in
                                zip(out_b, strat.out_placements))
                            if sharded:
                                flops /= n  # any sharded dim splits MACs
                            t += max(flops / edconfig.peak_flops,
                                     nbytes / edconfig.hbm_bandwidth)
                        else:
                            # memory-bound ops keep the conservative
                            # output-bytes proxy: pricing their input
                            # traffic too makes the ILP chase ZeRO-style
                            # param scatter at toy scale, where the per-
                            # collective alpha dwarfs the savings (the
                            # dp x tp never-costlier gate pins this)
                            full_t = sum(out_b) * inv_hbm
                            t += full_t * ((1.0 / n) if sharded else 1.0)
                    # composite ops (scan bodies) carry their internal
                    # per-strategy collective seconds here
                    t += getattr(strat, "intrinsic_cost", 0.0)
                if t > 0.0:
                    if costs is None:
                        costs = np.zeros(c.strategy_count())
                    costs[s] = t
            if costs is not None:
                self.output_y_cost[c.cid] = costs
        if op_times and n_comp:
            logger.info("[SpmdSolver] op-cost DB hit rate %d/%d (%.0f%%)",
                        n_hit, n_comp, 100.0 * n_hit / n_comp)
        state_outs = set(self.graph.state_io)
        for var in self.graph.outputs:
            if self.free_outputs or var.name in state_outs \
                    or var.producer is None:
                continue
            c = by_cid[var.producer.cluster_id]
            costs = self.output_y_cost.setdefault(
                c.cid, np.zeros(c.strategy_count()))
            for s in range(c.strategy_count()):
                p = c.strategies[s][var.producer.uid][1].out_placements[
                    var.producer_idx]
                if p is not None:
                    costs[s] += resharding_cost(var.size_bytes(), p,
                                                Placement.replicate(), self.axis)

    def _build_matrices(self):
        for e in self.edges:
            n_up = e.up_cluster.strategy_count()
            n_down = e.down_cluster.strategy_count()
            comm = np.zeros((n_up, n_down))
            mem = np.zeros((n_up, n_down))
            size = e.var.size_bytes()
            for i in range(n_up):
                pu = e.up_placement(i)
                for j in range(n_down):
                    pd = e.down_placement(j)
                    if pu is None or pd is None:
                        continue
                    comm[i, j] = resharding_cost(size, pu, pd, self.axis)
                    mem[i, j] = (placement_bytes(size, pu, self.axis.size)
                                 + placement_bytes(size, pd, self.axis.size))
                    # a P edge carries an unrealized reduction: when a
                    # deferred plan is comm-byte-NEUTRAL (psum at the fence
                    # costs what the immediate psum did), prefer the
                    # immediate one — full-size partials inflate liveness
                    # and block remat for no wire saving.  Epsilon-scale so
                    # it can never flip a genuinely byte-saving deferral.
                    if (pu is not None and pu.is_partial()) \
                            or (pd is not None and pd.is_partial()):
                        mem[i, j] += 1e-3 * size
            if self.reachability is not None and edconfig.predict_comm_overlap:
                # overlap-capable collectives cost less — but only as much
                # as the independent compute can actually hide (the
                # reference's flat discount, adjust_resharding_cost
                # solver.py:79-84, fires on ANY parallel flops; here the
                # hideable seconds bound the reduction per edge, and the
                # ratio comes from overlap_discount_ratio(): the runtime-
                # MEASURED fraction when calibrate_overlap has recorded
                # one, else the configured guess (per
                # comm_overlap_ratio_source)
                ratio = overlap_discount_ratio()
                hideable = self.reachability.independent_peer_seconds(
                    e.up_node.name, e.down_node.name)
                if hideable > 0 and ratio > 0:
                    comm = comm - ratio * np.minimum(comm, hideable)
            e.comm, e.mem = comm, mem

    def _compute_tie_groups(self):
        """Weisfeiler-Lehman style refinement: clusters with identical
        strategy tables AND isomorphic cost environments collapse to one
        representative.  Tying restricts the solution space to uniform
        per-type strategies — exactly the repeated-layer optimum."""
        import hashlib

        def sig(c):
            parts = [str(c.strategy_count())]
            for uid, node in c.nodes.items():
                parts.append(str([None if v is None else v.size_bytes()
                                  for v in node.invars]))
                parts.append(str([None if v is None else v.size_bytes()
                                  for v in node.outvars]))
            for s in range(c.strategy_count()):
                for uid, (_, st) in c.strategies[s].items():
                    parts.append(f"{st.in_placements}>{st.out_placements}")
            yc = self.output_y_cost.get(c.cid)
            parts.append("-" if yc is None else yc.tobytes().hex())
            return hashlib.sha256("|".join(parts).encode()).hexdigest()

        h = {c.cid: sig(c) for c in self.clusters}
        # ONE refinement round: content + immediate cost environment.  More
        # rounds would progressively split a repeated-layer chain from both
        # ends (layer 2's depth-2 environment sees the distinct embedding),
        # reverting the dedup; one round keeps boundary layers separate
        # (where tying is actually risky) and ties the middle.
        for _ in range(1):
            env: Dict[int, list] = {c.cid: [] for c in self.clusters}
            for e in self.edges:
                ekey = hashlib.sha256(
                    e.comm.tobytes() + e.mem.tobytes()
                    + f"{e.out_idx}:{e.in_idx}".encode()).hexdigest()
                env[e.up_cluster.cid].append(
                    f"out:{ekey}:{h[e.down_cluster.cid]}")
                env[e.down_cluster.cid].append(
                    f"in:{ekey}:{h[e.up_cluster.cid]}")
            h = {c.cid: hashlib.sha256(
                    (h[c.cid] + "|".join(sorted(env[c.cid]))).encode()
                 ).hexdigest() for c in self.clusters}

        first: Dict[str, int] = {}
        for c in self.clusters:
            self.tie_rep[c.cid] = first.setdefault(h[c.cid], c.cid)
        n_rep = len(set(self.tie_rep.values()))
        if n_rep < len(self.clusters):
            logger.info("[SpmdSolver] tied %d clusters into %d groups",
                        len(self.clusters), n_rep)

    def _picks_comm_cost(self, picks: Dict[int, int]) -> float:
        """Edge-communication cost of a {cid: strategy_idx} solution."""
        return float(sum(
            e.comm[picks[e.up_cluster.cid], picks[e.down_cluster.cid]]
            for e in self.edges))

    def assignment_comm_cost(self, chosen: Dict[str, NodeStrategy]) -> float:
        """Pure edge-communication cost of a node-strategy assignment
        (no y costs): 0.0 means sync-free."""
        pick: Dict[int, int] = {}
        for c in self.clusters:
            for s in range(c.strategy_count()):
                if all(c.strategies[s][uid][1]
                       == chosen.get(c.nodes[uid].name)
                       for uid in c.strategies[s]):
                    pick[c.cid] = s
                    break
            else:
                return float("inf")
        return sum(e.comm[pick[e.up_cluster.cid], pick[e.down_cluster.cid]]
                   for e in self.edges)

    # ----------------------------------------------------------------- solve

    def solve(self) -> Dict[str, NodeStrategy]:
        if edconfig.solver_backend == "beam" or not self.edges:
            return self.beam_search()
        try:
            return self._ilp_solve()
        except Exception:
            logger.exception("ILP solve failed; falling back to beam search")
            return self.beam_search()

    def _ilp_solve(self, apply_memory_cap: bool = True
                   ) -> Dict[str, NodeStrategy]:
        start = time.perf_counter()
        rep = self.tie_rep
        rep_clusters = [c for c in self.clusters if rep[c.cid] == c.cid]

        y_offset: Dict[int, int] = {}
        nvar = 0
        for c in rep_clusters:
            y_offset[c.cid] = nvar
            nvar += c.strategy_count()
        n_y = nvar

        # tied edges with identical cost matrices collapse into one z block
        # with a multiplicity weight
        groups: Dict[tuple, list] = {}
        for e in self.edges:
            key = (rep[e.up_cluster.cid], rep[e.down_cluster.cid],
                   e.comm.tobytes(), e.mem.tobytes())
            if key in groups:
                groups[key][0] += 1
            else:
                groups[key] = [1, e]
        edge_groups = list(groups.values())
        for _, e in edge_groups:
            e.z_offset = nvar
            nvar += e.up_cluster.strategy_count() * e.down_cluster.strategy_count()

        # objective = comm (dominant) + memory (strict tie-breaker).
        # Comm is rescaled to O(1): raw costs in seconds (~1e-8) sit below
        # HiGHS's default tolerances, which silently accepts suboptimal
        # incumbents.  Memory is then scaled so that the TOTAL memory term
        # stays below the smallest nonzero comm difference — it can order
        # comm-equivalent solutions (shard beats replicate) but never flip a
        # real comm decision.
        comm = np.zeros(nvar)
        mem = np.zeros(nvar)
        for count, e in edge_groups:
            comm[e.z_offset:e.z_offset + e.comm.size] = count * e.comm.ravel()
            mem[e.z_offset:e.z_offset + e.mem.size] = count * e.mem.ravel()
        for cid, costs in self.output_y_cost.items():
            off = y_offset[rep[cid]]
            comm[off:off + costs.size] += costs
        cost_scale = float(comm.max())
        if cost_scale > 0:
            comm = comm / cost_scale
        positive = comm[comm > 0]
        min_comm_step = positive.min() if positive.size else 1.0
        mem_max = float(mem.max())
        if mem_max > 0:
            n_active = max(len(edge_groups), 1)
            mem = mem * (min_comm_step / (10.0 * n_active * mem_max))
        cost = comm + mem

        rows, cols, vals, lbs, ubs = [], [], [], [], []
        row = 0
        # one-hot cluster choice
        for c in rep_clusters:
            for s in range(c.strategy_count()):
                rows.append(row); cols.append(y_offset[c.cid] + s); vals.append(1.0)
            lbs.append(1.0); ubs.append(1.0)
            row += 1
        # marginal (transportation) formulation — tighter LP relaxation than
        # z >= y_up + y_down - 1 and fewer rows (n_up + n_down per edge):
        #   sum_j z[i, j] == y_up[i],  sum_i z[i, j] == y_down[j]
        # with integral y the z become exactly the indicator of the chosen
        # pair; the LP picks the cheapest joint consistent with the
        # marginals.  (A self-type edge's rows stay valid: both marginal
        # systems constrain the same tied y vector.)
        for _, e in edge_groups:
            n_up = e.up_cluster.strategy_count()
            n_down = e.down_cluster.strategy_count()
            up_off = y_offset[rep[e.up_cluster.cid]]
            down_off = y_offset[rep[e.down_cluster.cid]]
            for i in range(n_up):
                for j in range(n_down):
                    rows.append(row)
                    cols.append(e.z_offset + i * n_down + j)
                    vals.append(1.0)
                rows.append(row); cols.append(up_off + i); vals.append(-1.0)
                lbs.append(0.0); ubs.append(0.0)
                row += 1
            for j in range(n_down):
                for i in range(n_up):
                    rows.append(row)
                    cols.append(e.z_offset + i * n_down + j)
                    vals.append(1.0)
                rows.append(row); cols.append(down_off + j); vals.append(-1.0)
                lbs.append(0.0); ubs.append(0.0)
                row += 1

        # optional hard memory cap per liveness step
        cap = edconfig.per_device_memory_cap if apply_memory_cap else 0
        if cap > 0:
            cap_eff = cap * edconfig.memory_ratio
            producer_cluster = {}
            for c in self.clusters:
                for n in c.nodes.values():
                    # liveness_only_input: cap only placeholder tensors
                    # (params/state dominate; activations churn fast —
                    # reference config.liveness_only_input)
                    if edconfig.liveness_only_input and not n.is_input:
                        continue
                    for v in n.outvars:
                        if v is not None:
                            producer_cluster[v.name] = (c, n, v.producer_idx)
            for live in self.graph.liveness():
                any_entry = False
                for v in live:
                    hit = producer_cluster.get(v.name)
                    if hit is None:
                        continue
                    c, n, out_idx = hit
                    for s in range(c.strategy_count()):
                        p = c.strategies[s][n.uid][1].out_placements[out_idx]
                        if p is None:
                            continue
                        rows.append(row)
                        cols.append(y_offset[rep[c.cid]] + s)
                        vals.append(placement_bytes(v.size_bytes(), p,
                                                    self.axis.size))
                        any_entry = True
                if any_entry:
                    lbs.append(-np.inf); ubs.append(cap_eff)
                    row += 1

        A = sparse.csr_matrix((vals, (rows, cols)), shape=(row, nvar))
        integrality = np.zeros(nvar)
        integrality[:n_y] = 1
        res = milp(c=cost,
                   constraints=LinearConstraint(A, np.array(lbs), np.array(ubs)),
                   integrality=integrality,
                   bounds=Bounds(0, 1),
                   options={"time_limit": edconfig.solver_time_limit,
                            # plateaus of equal-cost optima (latency and
                            # compute terms quantize) make optimality proofs
                            # explode; a small gap ends the search early
                            "mip_rel_gap": edconfig.solver_mip_rel_gap})
        # status 1 = iteration/time limit: keep the incumbent if HiGHS found one
        if res.x is None or res.status not in (0, 1):
            if apply_memory_cap and edconfig.per_device_memory_cap > 0 \
                    and res.status == 2:
                # no sharding assignment satisfies the liveness cap: solve
                # for minimum communication uncapped — the downstream remat
                # pass (schedule/remat.py) closes the remaining memory gap
                logger.warning(
                    "[SpmdSolver] liveness cap %.2f GiB infeasible on axis "
                    "%s; re-solving uncapped (auto-remat takes over)",
                    edconfig.per_device_memory_cap * edconfig.memory_ratio
                    / 2**30, self.axis.name)
                # the capped model ran untied (only non-uniform assignments
                # can dodge a cap); the uncapped fallback must re-tie, or
                # the larger untied ILP lands on a different near-tie than
                # the cap-0 solve and the remat planner sees a worse plan
                if self.cluster_dedup:
                    self._compute_tie_groups()
                return self._ilp_solve(apply_memory_cap=False)
            raise RuntimeError(f"MILP failed: status={res.status} {res.message}")
        logger.info("[SpmdSolver] axis=%s clusters=%d (%d tied) edges=%d "
                    "(%d grouped) vars=%d cost=%.3e time=%.2fs",
                    self.axis.name, len(self.clusters), len(rep_clusters),
                    len(self.edges), len(edge_groups), nvar, res.fun,
                    time.perf_counter() - start)

        picks: Dict[int, int] = {}
        for c in self.clusters:
            off = y_offset[rep[c.cid]]
            ys = res.x[off:off + c.strategy_count()]
            picks[c.cid] = int(np.argmax(ys))
        # Local refinement always runs: it recovers per-instance deviations
        # the tied quotient model cannot express AND deterministically
        # enforces the memory tie-break that mip_rel_gap's early stop may
        # leave on the table (the gap tolerance is orders of magnitude
        # larger than the scaled memory term).  Strictly monotone in the
        # untied objective.
        picks = self._refine(picks, capped=(
            apply_memory_cap and edconfig.per_device_memory_cap > 0))
        self.last_comm_cost = self._picks_comm_cost(picks)

        chosen: Dict[str, NodeStrategy] = {}
        for c in self.clusters:
            for uid, (_, strat) in c.strategies[picks[c.cid]].items():
                chosen[c.nodes[uid].name] = strat
        return chosen

    def _refine(self, picks: Dict[int, int], max_sweeps: int = 10,
                capped: bool = False) -> Dict[int, int]:
        """Coordinate descent on the full (untied) model: re-pick each
        cluster's strategy given its neighbors until a fixed point."""
        if capped:
            # a local move could break the per-liveness-step cap the ILP
            # enforced; keep the capped solution as-is.  (The uncapped
            # FALLBACK solve does refine — its model has no cap to break,
            # and skipping left a memory-worse near-tie for remat.)
            return picks
        in_edges: Dict[int, List[_Edge]] = {}
        out_edges: Dict[int, List[_Edge]] = {}
        for e in self.edges:
            in_edges.setdefault(e.down_cluster.cid, []).append(e)
            out_edges.setdefault(e.up_cluster.cid, []).append(e)
        all_comm = [c for e in self.edges for c in e.comm.ravel() if c > 0]
        min_comm = min(all_comm) if all_comm else 1.0
        max_mem = max((float(e.mem.max()) for e in self.edges), default=0.0)
        w_mem = (min_comm / (10.0 * max(len(self.edges), 1) * max_mem)
                 if max_mem > 0 else 0.0)
        eps = 1e-12

        def local_cost(c, s):
            cost = 0.0
            yc = self.output_y_cost.get(c.cid)
            if yc is not None:
                cost += float(yc[s])
            for e in in_edges.get(c.cid, []):
                i = picks[e.up_cluster.cid]
                cost += e.comm[i, s] + w_mem * e.mem[i, s]
            for e in out_edges.get(c.cid, []):
                j = picks[e.down_cluster.cid]
                cost += e.comm[s, j] + w_mem * e.mem[s, j]
            return cost

        by_cid = {c.cid: c for c in self.clusters}

        def local_cost_overlay(c, s, overlay):
            # edges into the moving region get a hair more weight so that a
            # locally-indifferent node follows the chain instead of stalling
            # the propagation at a tie (acceptance still uses true cost)
            cost = 0.0
            yc = self.output_y_cost.get(c.cid)
            if yc is not None:
                cost += float(yc[s])
            for e in in_edges.get(c.cid, []):
                up = e.up_cluster.cid
                i = overlay.get(up, picks[up])
                w = 1.0 + 1e-6 if up in overlay else 1.0
                cost += w * (e.comm[i, s] + w_mem * e.mem[i, s])
            for e in out_edges.get(c.cid, []):
                dn = e.down_cluster.cid
                j = overlay.get(dn, picks[dn])
                w = 1.0 + 1e-6 if dn in overlay else 1.0
                cost += w * (e.comm[s, j] + w_mem * e.mem[s, j])
            return cost

        def region_cost(cids, overlay):
            total = 0.0
            seen = set()
            for cid in cids:
                c = by_cid[cid]
                s = overlay.get(cid, picks[cid])
                yc = self.output_y_cost.get(cid)
                if yc is not None:
                    total += float(yc[s])
                for e in in_edges.get(cid, []) + out_edges.get(cid, []):
                    if id(e) in seen:
                        continue
                    seen.add(id(e))
                    i = overlay.get(e.up_cluster.cid,
                                    picks[e.up_cluster.cid])
                    j = overlay.get(e.down_cluster.cid,
                                    picks[e.down_cluster.cid])
                    total += e.comm[i, j] + w_mem * e.mem[i, j]
            return total

        def try_flip(root, s_root, cap=64):
            """Ejection chain: flip `root` to `s_root`, propagate each
            neighbor's best response (tied optimizer chains are coupled
            through zero-cost-when-consistent edges, so a profitable flip
            only shows up when the whole chain moves), accept if the
            affected region got cheaper."""
            overlay = {root.cid: s_root}
            frontier = [root]
            while frontier and len(overlay) < cap:
                c = frontier.pop()
                peers = [e.up_cluster for e in in_edges.get(c.cid, [])] + \
                        [e.down_cluster for e in out_edges.get(c.cid, [])]
                for q in peers:
                    if q.cid in overlay:
                        continue
                    costs = [local_cost_overlay(q, s, overlay)
                             for s in range(q.strategy_count())]
                    s_q = int(np.argmin(costs))
                    if s_q != picks[q.cid] \
                            and costs[s_q] < costs[picks[q.cid]] - 1e-18:
                        overlay[q.cid] = s_q
                        frontier.append(q)
            cids = list(overlay)
            if region_cost(cids, overlay) < region_cost(cids, {}) - eps:
                picks.update(overlay)
                return True
            return False

        moves = 0
        for _ in range(max_sweeps):
            changed = False
            for c in self.clusters:
                # cheap single move first, ejection chain if it is blocked
                cur = picks[c.cid]
                cur_cost = local_cost(c, cur)
                for s in range(c.strategy_count()):
                    if s == cur:
                        continue
                    if local_cost(c, s) < cur_cost - eps:
                        picks[c.cid] = s
                        cur, cur_cost = s, local_cost(c, s)
                        changed = True
                        moves += 1
                    elif try_flip(c, s):
                        cur, cur_cost = picks[c.cid], local_cost(
                            c, picks[c.cid])
                        changed = True
                        moves += 1
            if not changed:
                break
        if moves:
            logger.info("[SpmdSolver] refinement applied %d moves", moves)
        return picks

    # ----------------------------------------------------------- beam search

    def beam_search(self, width: Optional[int] = None) -> Dict[str, NodeStrategy]:
        """Greedy beam over clusters in order (reference solver.py:814-890)."""
        width = width or edconfig.beam_width
        # an edge's cost is charged when its SECOND endpoint gets assigned, so
        # edges in either direction (incl. state_io edges, whose producer
        # cluster comes after the placeholder consumer) are all priced
        in_edges: Dict[int, List[_Edge]] = {}
        out_edges: Dict[int, List[_Edge]] = {}
        for e in self.edges:
            in_edges.setdefault(e.down_cluster.cid, []).append(e)
            out_edges.setdefault(e.up_cluster.cid, []).append(e)

        # same comm >> memory hierarchy as the ILP objective
        all_comm = [c for e in self.edges for c in e.comm.ravel() if c > 0]
        min_comm = min(all_comm) if all_comm else 1.0
        max_mem = max((float(e.mem.max()) for e in self.edges), default=0.0)
        w_mem = (min_comm / (10.0 * max(len(self.edges), 1) * max_mem)
                 if max_mem > 0 else 0.0)

        # hot loop: prefer the native C++ beam core when built
        from easydist_tpu import native

        pos = {c.cid: i for i, c in enumerate(self.clusters)}
        if native.available():
            strat_count = [c.strategy_count() for c in self.clusters]
            y_cost_list = [
                np.asarray(self.output_y_cost.get(c.cid,
                                                  np.zeros(c.strategy_count())))
                for c in self.clusters]
            n_edges = [(pos[e.up_cluster.cid], pos[e.down_cluster.cid],
                        e.comm + w_mem * e.mem) for e in self.edges]
            res = native.beam_search_native(strat_count, y_cost_list, n_edges,
                                            width)
            if res is not None:
                assign, best_cost = res
                logger.info("[SpmdSolver.beam/native] axis=%s cost=%.3e",
                            self.axis.name, best_cost)
                self.last_comm_cost = self._picks_comm_cost(
                    {c.cid: int(assign[pos[c.cid]]) for c in self.clusters})
                chosen: Dict[str, NodeStrategy] = {}
                for c in self.clusters:
                    for uid, (_, strat) in \
                            c.strategies[int(assign[pos[c.cid]])].items():
                        chosen[c.nodes[uid].name] = strat
                return chosen
        # beam entries: (cost, {cid: strategy_idx})
        beam: List[Tuple[float, Dict[int, int]]] = [(0.0, {})]
        for c in self.clusters:
            grown: List[Tuple[float, Dict[int, int]]] = []
            out_cost = self.output_y_cost.get(c.cid)
            for base_cost, assign in beam:
                for s in range(c.strategy_count()):
                    delta = 0.0 if out_cost is None else float(out_cost[s])
                    for e in in_edges.get(c.cid, []):
                        i = assign.get(e.up_cluster.cid)
                        if i is not None:
                            delta += e.comm[i, s] + w_mem * e.mem[i, s]
                    for e in out_edges.get(c.cid, []):
                        j = assign.get(e.down_cluster.cid)
                        if j is not None and e.down_cluster.cid != c.cid:
                            delta += e.comm[s, j] + w_mem * e.mem[s, j]
                    grown.append((base_cost + delta, {**assign, c.cid: s}))
            grown.sort(key=lambda t: t[0])
            beam = grown[:width]

        best_cost, best = beam[0]
        logger.info("[SpmdSolver.beam] axis=%s cost=%.3e", self.axis.name,
                    best_cost)
        self.last_comm_cost = self._picks_comm_cost(best)
        chosen: Dict[str, NodeStrategy] = {}
        for c in self.clusters:
            for uid, (_, strat) in c.strategies[best[c.cid]].items():
                chosen[c.nodes[uid].name] = strat
        return chosen
