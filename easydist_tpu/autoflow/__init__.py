"""Auto-parallel strategy solver (reference: easydist/autoflow/)."""

from .cost_model import MeshAxisSpec, resharding_cost, placement_bytes  # noqa: F401
from .solver import SpmdSolver  # noqa: F401
