"""Reachability map: comm/compute-overlap awareness for the cost model.

Reference: easydist/torch/reachability.py (bitarray transitive closure +
FlopCounterMode) feeding the overlap discount in solver.py:74-84 — a
resharding collective whose producer and consumer have heavy *independent*
compute nearby can overlap with that compute, so its effective cost shrinks
by `comm_overlap_ratio`.

The closure is a dense numpy bool matrix (row i = descendants of op i;
column i = its ancestors), built in one reverse-topological vectorized
sweep; per-edge independent peer time is then a single vectorized mask.

Op time model: MXU-bound ops (dots/convs) are priced FLOPs/peak_flops;
everything else is memory-bound on TPU, priced bytes_touched/hbm_bandwidth —
a flat FLOP count at MXU peak would under-state elementwise/reduce time by
~100x and starve the overlap discount of precisely the ops that pipeline
best with collectives."""

from __future__ import annotations

import math
from typing import Dict

import numpy as np

from easydist_tpu import config as edconfig
from easydist_tpu.metashard.metair import MetaGraph, MetaNode

_HEAVY_OPS = {"dot_general", "conv_general_dilated", "matmul", "mm", "bmm",
              "dot"}


def _node_flops(node: MetaNode) -> float:
    if node.op_key not in _HEAVY_OPS:
        return 0.0
    if node.flops is not None:
        return node.flops  # exact MACs recorded by the bridge
    out_elems = sum(math.prod(v.shape) for v in node.outvars if v is not None)
    ins = [math.prod(v.shape) for v in node.invars if v is not None]
    if len(ins) >= 2 and out_elems > 0:
        # fallback for synthetic nodes (no recorded flops): for an
        # unbatched (M,K)x(K,N)->(M,N), in0*in1/out = K^2 exactly; batched
        # dots are ambiguous from shapes alone, which is why the bridge
        # records exact MACs for real graphs (r5 review #3).  The sqrt
        # inflates by sqrt(B) on a batched (B,M,K)x(B,K,N) dot, so clamp
        # by the largest input dim — the contraction length can never
        # exceed it (ADVICE r5: inflated stage-balance estimates)
        k = math.sqrt(max(ins[0], 1) * max(ins[1], 1) / out_elems)
        max_dim = max((d for v in node.invars if v is not None
                       for d in v.shape), default=1)
        k = min(k, float(max_dim))
    else:
        k = max(max(ins, default=0) / max(out_elems, 1), 1.0)
    return 2.0 * out_elems * max(k, 1.0)


def _node_seconds(node: MetaNode) -> float:
    """Estimated single-device run time of one op: the roofline
    max(MXU time, HBM time) — a small matmul is bandwidth-bound even
    though it runs on the MXU, and a big one is FLOPs-bound."""
    nbytes = sum(v.size_bytes() for v in node.invars if v is not None) \
        + sum(v.size_bytes() for v in node.outvars if v is not None)
    return max(_node_flops(node) / edconfig.peak_flops,
               nbytes / edconfig.hbm_bandwidth)


# public name: the jaxfront composite-discovery pricer uses the same
# roofline estimate when it prices control-flow body strategies
node_seconds = _node_seconds


class ReachabilityMap:
    """Transitive closure over graph ops + per-edge independent peer FLOPs."""

    def __init__(self, graph: MetaGraph):
        ops = graph.ops
        n = len(ops)
        self.index: Dict[str, int] = {op.name: i for i, op in enumerate(ops)}
        self.flops = np.array([_node_flops(op) for op in ops])
        self.seconds = np.array([_node_seconds(op) for op in ops])

        reach = np.zeros((n, n), dtype=bool)
        for i in reversed(range(n)):
            reach[i, i] = True
            for v in ops[i].outvars:
                if v is None:
                    continue
                for consumer, _ in v.consumers:
                    j = self.index.get(consumer.name)
                    if j is not None and j != i:
                        reach[i] |= reach[j]
        self.reach = reach
        self.n = n

    def _independent_mask(self, producer: str, consumer: str):
        i = self.index.get(producer)
        j = self.index.get(consumer)
        if i is None or j is None or self.n == 0:
            return None
        return ~(self.reach[i] | self.reach[j]
                 | self.reach[:, i] | self.reach[:, j])

    def independent_peer_flops(self, producer: str, consumer: str) -> float:
        """FLOPs of ops independent of both endpoints (neither ancestor nor
        descendant of either) — work a collective between them could hide
        behind."""
        mask = self._independent_mask(producer, consumer)
        return 0.0 if mask is None else float(self.flops[mask].sum())

    def independent_peer_seconds(self, producer: str, consumer: str) -> float:
        """Estimated seconds of independent peer work (MXU ops at
        peak_flops, memory-bound ops at hbm_bandwidth) — the time budget a
        collective between producer and consumer can hide inside."""
        mask = self._independent_mask(producer, consumer)
        return 0.0 if mask is None else float(self.seconds[mask].sum())
