"""Reachability map: comm/compute-overlap awareness for the cost model.

Reference: easydist/torch/reachability.py (bitarray transitive closure +
FlopCounterMode) feeding the overlap discount in solver.py:74-84 — a
resharding collective whose producer and consumer have heavy *independent*
compute nearby can overlap with that compute, so its effective cost shrinks
by `comm_overlap_ratio`.

The closure is a dense numpy bool matrix (row i = descendants of op i;
column i = its ancestors), built in one reverse-topological vectorized
sweep; per-edge independent FLOPs are then single vectorized masks."""

from __future__ import annotations

import math
from typing import Dict

import numpy as np

from easydist_tpu.metashard.metair import MetaGraph, MetaNode

_HEAVY_OPS = {"dot_general", "conv_general_dilated", "matmul", "mm", "bmm",
              "dot"}


def _node_flops(node: MetaNode) -> float:
    if node.op_key not in _HEAVY_OPS:
        return 0.0
    out_elems = sum(math.prod(v.shape) for v in node.outvars if v is not None)
    # contraction length ~ largest input size over output size
    in_elems = max((math.prod(v.shape) for v in node.invars if v is not None),
                   default=0)
    k = max(in_elems / max(out_elems, 1), 1.0)
    return 2.0 * out_elems * min(k, in_elems)


class ReachabilityMap:
    """Transitive closure over graph ops + per-edge independent peer FLOPs."""

    def __init__(self, graph: MetaGraph):
        ops = graph.ops
        n = len(ops)
        self.index: Dict[str, int] = {op.name: i for i, op in enumerate(ops)}
        self.flops = np.array([_node_flops(op) for op in ops])

        reach = np.zeros((n, n), dtype=bool)
        for i in reversed(range(n)):
            reach[i, i] = True
            for v in ops[i].outvars:
                if v is None:
                    continue
                for consumer, _ in v.consumers:
                    j = self.index.get(consumer.name)
                    if j is not None and j != i:
                        reach[i] |= reach[j]
        self.reach = reach
        self.n = n

    def independent_peer_flops(self, producer: str, consumer: str) -> float:
        """FLOPs of ops independent of both endpoints (neither ancestor nor
        descendant of either) — work a collective between them could hide
        behind."""
        i = self.index.get(producer)
        j = self.index.get(consumer)
        if i is None or j is None or self.n == 0:
            return 0.0
        related = (self.reach[i] | self.reach[j]
                   | self.reach[:, i] | self.reach[:, j])
        return float(self.flops[~related].sum())
