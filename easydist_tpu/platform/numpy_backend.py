"""NumPy implementation of the platform micro-API — hardware-free unit testing.

Gives the metashard engine a backend with zero accelerator or XLA dependency
(the reference's equivalent is easydist/platform/torch.py run on CPU).
"""

import numpy as np

from easydist_tpu import config as edconfig

Tensor = np.ndarray


def add(x, y):
    return np.add(x, y)


def equal(x, y):
    return x.shape == y.shape and bool(np.array_equal(x, y))


def allclose(x, y):
    if getattr(x, "shape", None) != getattr(y, "shape", None):
        return False
    return bool(np.allclose(x, y, rtol=edconfig.allclose_rtol, atol=edconfig.allclose_atol))


def zeros_like(x):
    return np.zeros_like(x)


def minimum(x, y):
    return np.minimum(x, y)


def maximum(x, y):
    return np.maximum(x, y)


def concatenate(tensors, dim=0):
    return np.concatenate(tensors, axis=dim)


def chunk(tensor, chunks, dim=0):
    return np.split(tensor, chunks, axis=dim)


def narrow(tensor, dim, start, length):
    index = [slice(None)] * tensor.ndim
    index[dim] = slice(start, start + length)
    return tensor[tuple(index)]


def clone(x):
    return np.copy(x)


def stack(tensors, dim=0):
    return np.stack(tensors, axis=dim)


def batched_call(fn, flat_args, in_axes):
    """The numpy backend has no batched execution — raising here routes
    MetaOp back to its sequential per-shard loop (same results)."""
    raise RuntimeError("numpy backend has no batched probe execution")


def from_numpy(x):
    return np.asarray(x)


def to_numpy(x):
    return np.asarray(x)


def tree_flatten(tree):
    """Minimal pytree flatten over dict/list/tuple containers."""
    leaves = []

    def _flatten(node):
        if isinstance(node, dict):
            keys = sorted(node)
            return ("dict", keys, [_flatten(node[k]) for k in keys])
        if isinstance(node, (list, tuple)):
            kind = "list" if isinstance(node, list) else "tuple"
            return (kind, len(node), [_flatten(x) for x in node])
        leaves.append(node)
        return ("leaf",)

    spec = _flatten(tree)
    return leaves, spec


def tree_unflatten(leaves, spec):
    it = iter(leaves)

    def _unflatten(node):
        kind = node[0]
        if kind == "leaf":
            return next(it)
        if kind == "dict":
            _, keys, children = node
            return {k: _unflatten(c) for k, c in zip(keys, children)}
        _, _, children = node
        seq = [_unflatten(c) for c in children]
        return seq if kind == "list" else tuple(seq)

    return _unflatten(spec)
