"""JAX implementation of the platform micro-API (reference: easydist/platform/jax.py).

All ops here run eagerly.  Discovery executes thousands of tiny ops, so we pin
them to the host CPU device when `config.discovery_on_cpu` is set — compile-time
analysis should not occupy the TPU or pay device-transfer latency.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from easydist_tpu import config as edconfig

Tensor = jax.Array


@functools.lru_cache(maxsize=1)
def _cpu_device():
    return jax.local_devices(backend="cpu")[0]


def _maybe_cpu(fn):
    """Run `fn` with default device = host CPU (and jit disabled)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if edconfig.discovery_on_cpu:
            with jax.default_device(_cpu_device()):
                return fn(*args, **kwargs)
        return fn(*args, **kwargs)

    return wrapped


@_maybe_cpu
def add(x, y):
    return jnp.add(x, y)


@_maybe_cpu
def equal(x, y):
    if x.shape != y.shape:
        return False
    return bool(jnp.array_equal(x, y))


@_maybe_cpu
def allclose(x, y):
    if getattr(x, "shape", None) != getattr(y, "shape", None):
        return False
    return bool(jnp.allclose(x, y, rtol=edconfig.allclose_rtol, atol=edconfig.allclose_atol))


@_maybe_cpu
def zeros_like(x):
    return jnp.zeros_like(x)


@_maybe_cpu
def minimum(x, y):
    return jnp.minimum(x, y)


@_maybe_cpu
def maximum(x, y):
    return jnp.maximum(x, y)


@_maybe_cpu
def concatenate(tensors, dim=0):
    return jnp.concatenate(tensors, axis=dim)


@_maybe_cpu
def chunk(tensor, chunks, dim=0):
    """Split into `chunks` equal parts along `dim` (must divide evenly)."""
    return jnp.split(tensor, chunks, axis=dim)


@_maybe_cpu
def narrow(tensor, dim, start, length):
    return jax.lax.slice_in_dim(tensor, start, start + length, axis=dim)


def clone(x):
    return x  # jax arrays are immutable; aliasing is safe


@_maybe_cpu
def stack(tensors, dim=0):
    return jnp.stack(tensors, axis=dim)


@_maybe_cpu
def batched_call(fn, flat_args, in_axes):
    """Run `fn(*flat_args)` vmapped over the axis-0 entries of `in_axes`:
    one eager dispatch for all shards of a discovery candidate instead of
    nshards sequential calls (metashard.MetaOp._run_sharded_batched)."""
    return jax.vmap(fn, in_axes=in_axes)(*flat_args)


@_maybe_cpu
def from_numpy(x):
    return jnp.asarray(x)


def to_numpy(x):
    return np.asarray(x)


def tree_flatten(tree):
    return jax.tree_util.tree_flatten(tree)


def tree_unflatten(leaves, spec):
    return jax.tree_util.tree_unflatten(spec, leaves)
