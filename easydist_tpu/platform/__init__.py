"""Tensor micro-API that the ShardCombine engine runs on.

The discovery engine (metashard/) only needs ~15 tensor operations, so it is
kept framework-neutral behind this registry (reference:
easydist/platform/__init__.py:23-49).  Backends: "jax" (default; discovery runs
eagerly on the host CPU device) and "numpy" (hardware-free unit tests).  A torch
frontend reuses the same engine by converting through numpy.
"""

import importlib
import sys

_BACKEND_NAME = None
_BACKEND_MOD = None

# the operations every backend must provide
_API = [
    "Tensor", "add", "equal", "allclose", "zeros_like", "minimum", "maximum",
    "concatenate", "chunk", "narrow", "clone", "from_numpy", "to_numpy",
    "tree_flatten", "tree_unflatten", "stack", "batched_call",
]


def init_backend(name: str = "jax"):
    """Load a backend module and re-export its micro-API here."""
    global _BACKEND_NAME, _BACKEND_MOD
    mod = importlib.import_module(f"easydist_tpu.platform.{name}_backend")
    for fn in _API:
        if not hasattr(mod, fn):
            raise RuntimeError(f"backend {name!r} is missing platform op {fn!r}")
        setattr(sys.modules[__name__], fn, getattr(mod, fn))
    _BACKEND_NAME = name
    _BACKEND_MOD = mod
    return mod


def get_backend() -> str:
    return _BACKEND_NAME


def backend_initialized() -> bool:
    return _BACKEND_NAME is not None


def __getattr__(name):
    """Lazily initialize the default (jax) backend on first API access, so
    importing the package stays cheap and the numpy backend can be selected
    in jax-free environments."""
    if name in _API and _BACKEND_NAME is None:
        init_backend("jax")
        return getattr(sys.modules[__name__], name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
