"""Sharding-space annotations for ShardCombine discovery.

A `ShardSpace` assigns every dimension of every tensor argument of an op a
`DimSharding`.  Dimensions that carry the same nonzero `group` id must be
sharded *together* (e.g. the contraction dims of a matmul); group 0 means the
dimension cannot be sharded.  A `DimSharding` can additionally carry

- `halo`: each shard is padded with `halo.width` rows of its neighbours along
  `halo.dim` (needed by convolution/pooling windows), and
- `block`: a block-cyclic factor — the dim is first split into `block` blocks
  and each shard takes the matching slice of every block.

Reference semantics: easydist/metashard/annotation.py:22-131 (ShardDim /
ShardAnnotation) and halo.py:20-55.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import List, Optional

from easydist_tpu import platform


@dataclass
class HaloSpec:
    width: int
    dim: int

    def __repr__(self) -> str:
        return f"halo({self.width}@{self.dim})"


@dataclass
class DimSharding:
    """Sharding assignment of one tensor dimension inside a ShardSpace."""

    group: int = 0  # 0 = not shardable; dims sharing a group shard together
    block: int = 1  # block-cyclic factor
    halo: Optional[HaloSpec] = None

    def shardable(self) -> bool:
        return self.group > 0

    def __repr__(self) -> str:
        if self.group == 0:
            return "-"
        parts = [str(self.group)]
        if self.block > 1:
            parts.append(f"block={self.block}")
        if self.halo is not None:
            parts.append(repr(self.halo))
        return f"S({', '.join(parts)})"


class ShardSpace:
    """Per-tensor-per-dim `DimSharding` table describing an op's shard space.

    Example spaces discovered by the engine:
      matmul [m,k]x[k,n]:  [[S(1), S(2)], [S(2), S(3)]]
      relu   [a,b]:        [[S(1), S(2)]]
      layernorm [a,b,h]:   [[S(1), S(2), -]]
    """

    def __init__(self, table: List[List[DimSharding]]):
        self.table = table

    @staticmethod
    def for_tensors(tensors) -> "ShardSpace":
        return ShardSpace([[DimSharding() for _ in t.shape] for t in tensors])

    @staticmethod
    def for_args(flat_args) -> "ShardSpace":
        tensors = [a for a in flat_args if isinstance(a, platform.Tensor)]
        return ShardSpace.for_tensors(tensors)

    def max_group(self) -> int:
        return max((d.group for row in self.table for d in row), default=0)

    def truncate(self, max_group: int) -> "ShardSpace":
        """Copy with every group id above `max_group` reset to unshardable."""
        out = copy.deepcopy(self)
        for row in out.table:
            for i, d in enumerate(row):
                if d.group > max_group:
                    row[i] = DimSharding()
        return out

    def attach_halo(self, halo: Optional[HaloSpec], group: int) -> None:
        if halo is None:
            return
        for row in self.table:
            for d in row:
                if d.group == group:
                    d.halo = halo

    def group_dim(self, tensor_idx: int, group: int) -> Optional[int]:
        """First dim of tensor `tensor_idx` assigned to `group`, or None."""
        for dim_idx, d in enumerate(self.table[tensor_idx]):
            if d.group == group:
                return dim_idx
        return None

    def compatible_with_args(self, flat_args) -> bool:
        """True if this space's ranks line up with the tensor args (used to
        validate a cached/prompt space against new shapes)."""
        tensors = [a for a in flat_args if isinstance(a, platform.Tensor)]
        if len(tensors) != len(self.table):
            return False
        return all(t.ndim == len(row) for t, row in zip(tensors, self.table))

    def __len__(self) -> int:
        return len(self.table)

    def __getitem__(self, idx: int) -> List[DimSharding]:
        return self.table[idx]

    def __add__(self, other: "ShardSpace") -> "ShardSpace":
        return ShardSpace(self.table + other.table)

    def __repr__(self) -> str:
        return f"ShardSpace({self.table!r})"

    def __eq__(self, other) -> bool:
        if not isinstance(other, ShardSpace) or len(self.table) != len(other.table):
            return False
        for r1, r2 in zip(self.table, other.table):
            if len(r1) != len(r2):
                return False
            for d1, d2 in zip(r1, r2):
                if (d1.group, d1.block) != (d2.group, d2.block):
                    return False
        return True


def halo_pad(shards, halo: Optional[HaloSpec]):
    """Pad each shard with `halo.width` elements from its neighbours along
    `halo.dim` (reference halo.py:33-55).  Interior shards get both sides."""
    if halo is None or len(shards) < 2:
        return shards
    w, dim = halo.width, halo.dim
    padded = []
    for i, shard in enumerate(shards):
        pieces = [shard]
        if i > 0:
            prev = shards[i - 1]
            size = prev.shape[dim]
            if size < w:
                raise RuntimeError("halo width exceeds neighbour shard size")
            pieces.insert(0, platform.narrow(prev, dim, size - w, w))
        if i < len(shards) - 1:
            pieces.append(platform.narrow(shards[i + 1], dim, 0, w))
        padded.append(platform.concatenate(pieces, dim=dim))
    return padded
