"""MetaOp: execution-based SPMD sharding-rule discovery ("ShardCombine").

Wraps a single operator (`fn`, concrete `args`).  `discover()` searches the
space of input shardings: it assigns a shard *group* to at most one dimension
of each tensor argument, executes the op once per shard with those dimensions
split `nshards` ways, and accepts the assignment iff the per-shard outputs can
be recombined into the unsharded output (see combination.match_recombine).
Each accepted group becomes one SPMD strategy of the op: inputs SHARD on their
group dims, output placement given by the recombination kind.

Reference semantics: easydist/metashard/metaop.py:60-277 (search order,
halo-retry loop, prompt fast-path).  Implementation is fresh; discovery runs
eagerly on the host CPU (see platform.jax_backend).
"""

from __future__ import annotations

import copy
import logging
from typing import Callable, Dict, List, Optional, Tuple

from easydist_tpu import config as edconfig
from easydist_tpu import platform
from .annotation import DimSharding, HaloSpec, ShardSpace, halo_pad
from .combination import HaloHint, match_recombine

logger = logging.getLogger(__name__)

# process-wide probe accounting: every eager execution of an op under
# discovery (global run, per-shard candidate run, or one batched candidate
# bind) is one probe program.  jaxfront.discovery reads the delta around
# each trace to report probes_compiled without a layering inversion.
_PROBES = {"calls": 0}


def probe_calls() -> int:
    return _PROBES["calls"]


def reset_probe_calls() -> None:
    _PROBES["calls"] = 0


class MetaOp:

    def __init__(self, fn: Callable, args, kwargs=None,
                 nshards: Optional[int] = None, name: Optional[str] = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", repr(fn))
        self.nshards = nshards or edconfig.discovery_nshards
        # args are the op's positional arguments, kwargs its keyword
        # arguments — kept explicit so a dict-valued positional arg is never
        # mistaken for keywords
        self.flat_args, self.args_spec = platform.tree_flatten(
            (tuple(args), dict(kwargs or {})))
        self.tensor_indices = [i for i, a in enumerate(self.flat_args)
                               if isinstance(a, platform.Tensor)]

    # ------------------------------------------------------------- execution

    def _call(self, flat_args):
        _PROBES["calls"] += 1
        args, kwargs = platform.tree_unflatten(flat_args, self.args_spec)
        return self.fn(*args, **kwargs)

    def run_global(self):
        return self._call(list(self.flat_args))

    def _shard_tensor(self, tensor, dim: int, block: int, halo: Optional[HaloSpec]):
        """Split `tensor` into nshards along `dim`; block-cyclic if block > 1;
        halo-pad the shards afterwards."""
        if tensor.shape[dim] % (self.nshards * block) != 0:
            raise RuntimeError(
                f"dim {dim} of size {tensor.shape[dim]} not divisible into "
                f"{self.nshards} shards x {block} blocks")
        if block == 1:
            shards = platform.chunk(tensor, self.nshards, dim)
        else:
            blocks = platform.chunk(tensor, block, dim)
            per_block = [platform.chunk(b, self.nshards, dim) for b in blocks]
            shards = [platform.concatenate([pb[s] for pb in per_block], dim=dim)
                      for s in range(self.nshards)]
        return halo_pad(shards, halo)

    def run_sharded(self, space: ShardSpace, group: int,
                    halo: Optional[HaloSpec] = None) -> List:
        """Execute once per shard with the group's dims split; returns the list
        of per-shard outputs.  Raises RuntimeError when shapes don't divide."""
        shard_plans: Dict[int, List] = {}  # flat-arg index -> per-shard tensors
        for t_idx, flat_idx in enumerate(self.tensor_indices):
            row = space[t_idx]
            for dim_idx, d in enumerate(row):
                if d.group == group:
                    eff_halo = halo if halo is not None else d.halo
                    if eff_halo is not None:
                        # halo is always exchanged along the dim being split —
                        # a HaloHint's dim refers to the *output* concat dim
                        # and must not leak here
                        eff_halo = HaloSpec(eff_halo.width, dim_idx)
                    shard_plans[flat_idx] = self._shard_tensor(
                        self.flat_args[flat_idx], dim_idx, d.block, eff_halo)
                    break
        if not shard_plans:
            raise RuntimeError(f"group {group} not present in shard space")

        if edconfig.discovery_batch_probes and self.nshards > 1:
            try:
                return self._run_sharded_batched(shard_plans)
            except Exception as e:
                logger.debug("%s: batched probe fell back to the shard "
                             "loop: %s", self.name, e)

        outs = []
        for s in range(self.nshards):
            shard_args = list(self.flat_args)
            for flat_idx, shards in shard_plans.items():
                shard_args[flat_idx] = shards[s]
            outs.append(self._call(shard_args))
        return outs

    def _run_sharded_batched(self, shard_plans: Dict[int, List]) -> List:
        """Fuse the nshards per-shard executions of one candidate into a
        single batched bind: sharded operands stack along a fresh leading
        axis and the op runs vmapped over it (platform.batched_call).  One
        eager dispatch per candidate instead of nshards, with bitwise-equal
        per-shard outputs for every primitive whose batching rule is the op
        itself over slices.  Raises on non-uniform shard shapes (halo-padded
        edge shards) or unbatchable ops; the caller falls back to the loop."""
        stacked = list(self.flat_args)
        in_axes: List[Optional[int]] = [None] * len(stacked)
        for flat_idx, shards in shard_plans.items():
            if len({tuple(s.shape) for s in shards}) != 1:
                raise RuntimeError("non-uniform shard shapes")
            stacked[flat_idx] = platform.stack(shards, dim=0)
            in_axes[flat_idx] = 0

        def call_flat(*flat):
            args, kwargs = platform.tree_unflatten(list(flat),
                                                   self.args_spec)
            return self.fn(*args, **kwargs)

        out = platform.batched_call(call_flat, stacked, tuple(in_axes))
        _PROBES["calls"] += 1
        leaves, spec = platform.tree_flatten(out)
        if any(getattr(leaf, "ndim", 0) < 1
               or leaf.shape[0] != self.nshards for leaf in leaves):
            raise RuntimeError("batched output lost the shard axis")
        return [platform.tree_unflatten([leaf[s] for leaf in leaves], spec)
                for s in range(self.nshards)]

    # -------------------------------------------------------------- discovery

    def _check_candidate(self, space: ShardSpace, group: int, global_out):
        """Execute a candidate sharding and match recombination; drives the
        halo-retry loop (reference metaop.py:147-166).  Returns
        (recombine_fn_or_list, halo_used) or None."""
        try:
            sharded = self.run_sharded(space, group)
        except Exception as e:  # shape indivisible, op rejects sharded input, ...
            logger.debug("candidate %r failed to execute: %s", space, e)
            return None

        fn = match_recombine(sharded, global_out)
        if isinstance(fn, HaloHint):
            hint = fn
            width0 = max(hint.width, 1)
            sample = sharded[0][hint.out_idx] if hint.out_idx is not None else sharded[0]
            width_cap = max(sample.shape[hint.dim] // 2, width0)
            for width in range(width0, width_cap + 1):
                halo = HaloSpec(width, hint.dim)
                try:
                    sharded = self.run_sharded(space, group, halo=halo)
                except Exception:
                    return None
                fn = match_recombine(sharded, global_out)
                if fn is not None and not isinstance(fn, HaloHint):
                    return fn, halo
            return None
        if fn is None:
            return None
        return fn, None

    def _search_group(self, space: ShardSpace, group: int,
                      anchor: Tuple[int, int], global_out):
        """Find an assignment of `group` to >=1 currently-unsharded dims (at
        most one per tensor), whose first assigned dim is at/after `anchor`.
        Candidates are enumerated depth-first in (tensor, dim) order; the first
        that executes and recombines wins (reference metaop.py:130-188).

        Returns (new_space, recombine, halo) or None."""
        ntensors = len(space)

        def assignments(t_idx: int, chosen: List[Tuple[int, int]]):
            if t_idx == ntensors:
                if chosen:
                    yield list(chosen)
                return
            start = anchor[1] if t_idx == anchor[0] and not chosen else 0
            if not chosen and t_idx < anchor[0]:
                # first assigned dim must not precede the anchor tensor
                yield from assignments(t_idx + 1, chosen)
                return
            for dim_idx in range(start, len(space[t_idx])):
                if space[t_idx][dim_idx].group == 0:
                    chosen.append((t_idx, dim_idx))
                    yield from assignments(t_idx + 1, chosen)
                    chosen.pop()
            yield from assignments(t_idx + 1, chosen)

        budget = edconfig.discovery_max_candidates
        for chosen in assignments(0, []):
            budget -= 1
            if budget < 0:
                logger.debug("%s: candidate budget exhausted for group %d",
                             self.name, group)
                return None
            cand = copy.deepcopy(space)
            for t_idx, dim_idx in chosen:
                cand.table[t_idx][dim_idx] = DimSharding(group=group)
            res = self._check_candidate(cand, group, global_out)
            if res is not None:
                fn, halo = res
                cand.attach_halo(halo, group)
                return cand, fn, halo
        return None

    def discover(self, prompt: Optional[ShardSpace] = None):
        """Full sharding discovery.  Returns (ShardSpace, {group: recombine}).

        `prompt` is a space discovered for the same op at other shapes; its
        groups are re-validated cheaply before falling back to search
        (reference metaop.py:190-260, 262-277).
        """
        recombines: Dict[int, object] = {}
        space = ShardSpace.for_args(self.flat_args)
        global_out = self.run_global()

        if prompt is not None and prompt.compatible_with_args(self.flat_args):
            prompt_halos = {}
            for group in range(1, prompt.max_group() + 1):
                res = self._check_candidate(prompt, group, global_out)
                if res is None:
                    break
                recombines[group] = res[0]
                prompt_halos[group] = res[1]
            if recombines:
                space = prompt.truncate(len(recombines))
                for group, halo in prompt_halos.items():
                    if halo is not None:  # re-validation needed a new width
                        space.attach_halo(halo, group)

        group = len(recombines) + 1
        anchor = (0, 0)
        while anchor[0] < len(space):
            found = self._search_group(space, group, anchor, global_out)
            if found is None:
                break
            space, fn, _halo = found
            recombines[group] = fn
            # next group's first dim must come after this group's first dim
            pos = next(((t, d) for t in range(len(space))
                        for d in range(len(space[t]))
                        if space[t][d].group == group))
            t, d = pos
            anchor = (t, d + 1) if d + 1 < len(space[t]) else (t + 1, 0)
            group += 1

        logger.debug("discovered space of %s: %r", self.name, space)
        return space, recombines
