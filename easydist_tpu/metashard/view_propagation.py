"""Analytic sharding rule for reshape/view ops — no execution needed.

Aligns input and output shapes by scanning both left-to-right, accumulating
products until they agree; a dim that maps through the reshape intact (or is
the leftmost of a merged/split run) is shardable, and the output recombines by
concat on the aligned output dim.  Reference: metashard/view_propagation.py:33-129.
"""

from __future__ import annotations

import functools
import math
from typing import Dict, List, Optional

from .annotation import DimSharding, ShardSpace
from .combination import Recombine


def _skip_ones(shape, idx):
    while idx < len(shape) and shape[idx] == 1:
        idx += 1
    return idx


def view_rule(input_shape: List[int], output_shape: List[int], world_size: int = 1):
    """Sharding space + recombinations for reshape(input_shape -> output_shape).

    Returns {"space": ShardSpace (one row), "recombines": {group: fn}}.
    A dim is only made shardable when its size is at least `world_size`.
    """
    input_shape = list(input_shape)
    output_shape = list(output_shape)
    if -1 in output_shape:
        known = -math.prod(output_shape)
        output_shape[output_shape.index(-1)] = math.prod(input_shape) // known

    row = [DimSharding() for _ in input_shape]
    recombines: Dict[int, object] = {}
    group = 1

    i = _skip_ones(input_shape, 0)
    o = _skip_ones(output_shape, 0)

    def emit(in_dim: int, out_dim: int):
        nonlocal group
        if input_shape[in_dim] >= world_size \
                and input_shape[in_dim] % world_size == 0:
            row[in_dim] = DimSharding(group=group)
            recombines[group] = functools.partial(Recombine.concat, dim=out_dim)
            group += 1

    while i < len(input_shape) and o < len(output_shape):
        isz, osz = input_shape[i], output_shape[o]
        if isz == osz:
            # [.., A, ..] -> [.., A, ..]
            emit(i, o)
            i = _skip_ones(input_shape, i + 1)
            o = _skip_ones(output_shape, o + 1)
        elif isz > osz:
            # [.., A, ..] -> [.., a1, a2, ..] : shard A iff a1 (leftmost) big
            # enough; the shard boundary then falls between a1 slices
            acc, o_end = osz, o
            while acc < isz and o_end + 1 < len(output_shape):
                o_end += 1
                acc *= output_shape[o_end]
            if acc != isz:
                raise RuntimeError(
                    f"view_rule cannot align {input_shape} -> {output_shape}")
            # sharding input dim A = a1*a2*... maps to sharding a1 (leftmost of
            # the split run), so a1 itself must divide evenly across devices
            if output_shape[o] >= world_size and output_shape[o] % world_size == 0:
                emit(i, o)
            i = _skip_ones(input_shape, i + 1)
            o = _skip_ones(output_shape, o_end + 1)
        else:
            # [.., a1, a2, ..] -> [.., A, ..] : shard a1 (leftmost of run)
            acc, i_end = isz, i
            while acc < osz and i_end + 1 < len(input_shape):
                i_end += 1
                acc *= input_shape[i_end]
            if acc != osz:
                raise RuntimeError(
                    f"view_rule cannot align {input_shape} -> {output_shape}")
            emit(i, o)
            i = _skip_ones(input_shape, i_end + 1)
            o = _skip_ones(output_shape, o + 1)

    return {"space": ShardSpace([row]), "recombines": recombines}


def view_rule_for_space(input_shape: List[int], output_shape: List[int],
                        preset_row) -> Optional[object]:
    """Given a *preset* input sharding (first sharded dim of `preset_row`),
    find the matching output concat dim analytically
    (reference view_propagation.py:107-129)."""
    lead = 1
    for idx, d in enumerate(preset_row):
        if d.group != 0:
            break
        lead *= input_shape[idx]
    else:
        return None

    out_acc, out_idx = 1, 0
    while out_acc < lead and out_idx < len(output_shape):
        out_acc *= output_shape[out_idx]
        out_idx += 1
    if out_acc != lead:
        return None

    block = preset_row[idx].block
    acc_block = 1
    for o_idx in range(out_idx, len(output_shape) + 1):
        if block == acc_block:
            return functools.partial(Recombine.concat, dim=o_idx)
        if o_idx < len(output_shape):
            acc_block *= output_shape[o_idx]
    return None
