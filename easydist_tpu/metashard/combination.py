"""Recombination library: how sharded outputs re-form the global output.

`Recombine.*` are the recombination functions; `match_*` are checkers that
numerically compare a candidate recombination of the sharded outputs against
the global output and return the matching `functools.partial` on success.
The recombination kind directly names the SPMD placement of the output:

    identity      -> REPLICATE  (no collective)
    reduce(op)    -> PARTIAL    (all_reduce on the mesh axis)
    concat(dim)   -> SHARD(dim) (all_gather to reconstruct)

Reference semantics: easydist/metashard/combination.py:76-310.
"""

from __future__ import annotations

import functools
from enum import Enum
from typing import List, Optional

from easydist_tpu import config as edconfig
from easydist_tpu import platform


class Reduction(Enum):
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "avg"


class HaloHint:
    """Raised (as a return value) when the outputs look gatherable after halo
    padding of the *inputs* — signals the discovery loop to retry with halo."""

    def __init__(self, width: int, dim: int, out_idx: Optional[int] = None):
        self.width = width
        self.dim = dim
        self.out_idx = out_idx


class Recombine:

    @staticmethod
    def identity(parts):
        first = parts[0]
        for p in parts[1:]:
            if not platform.equal(first, p):
                return None
        return first

    @staticmethod
    def reduce(parts, op: Reduction = Reduction.SUM):
        if op in (Reduction.SUM, Reduction.AVG):
            acc = platform.zeros_like(parts[0])
            for p in parts:
                acc = platform.add(acc, p)
            if op is Reduction.AVG:
                acc = acc * (1.0 / len(parts))
            return acc
        fold = platform.maximum if op is Reduction.MAX else platform.minimum
        acc = parts[0]
        for p in parts[1:]:
            acc = fold(acc, p)
        return acc

    @staticmethod
    def concat(parts, dim: int, halo: int = 0, block: int = 1):
        """Concatenate along `dim`.

        halo > 0: adjacent shards share `halo` overlapping elements that must
        be summed (conv-style partial windows).
        halo < 0: each shard contributes `|halo|` too-few elements; drop the
        overlap symmetrically (gather of valid-conv outputs).
        block > 1: inverse of block-cyclic sharding — interleave the blocks.
        """
        if halo == 0:
            if block == 1:
                return platform.concatenate(parts, dim=dim)
            sub = [platform.chunk(p, block, dim) for p in parts]
            ordered = [sub[p][b] for b in range(block) for p in range(len(parts))]
            return platform.concatenate(ordered, dim=dim)

        acc = parts[0]
        for nxt in parts[1:]:
            a, b = acc.shape[dim], nxt.shape[dim]
            if halo > 0:
                overlap = platform.add(
                    platform.narrow(acc, dim, a - halo, halo),
                    platform.narrow(nxt, dim, 0, halo))
                acc = platform.concatenate(
                    [platform.narrow(acc, dim, 0, a - halo), overlap,
                     platform.narrow(nxt, dim, halo, b - halo)], dim=dim)
            else:
                acc = platform.concatenate(
                    [platform.narrow(acc, dim, 0, a + halo),
                     platform.narrow(nxt, dim, -halo, b + halo)], dim=dim)
        return acc


def _common_prefix_len(t1, t2, dim: int) -> int:
    """Length of the longest matching prefix of t1/t2 along `dim`
    (reference combination.py:48-58, vectorized to O(n))."""
    import numpy as np

    a, b = platform.to_numpy(t1), platform.to_numpy(t2)
    n = min(a.shape[dim], b.shape[dim])
    idx = np.arange(n)
    a, b = np.take(a, idx, axis=dim), np.take(b, idx, axis=dim)
    close = np.isclose(a, b, rtol=edconfig.allclose_rtol,
                       atol=edconfig.allclose_atol)
    other_axes = tuple(i for i in range(close.ndim) if i != dim)
    per_index = close.all(axis=other_axes) if other_axes else close
    if per_index.all():
        return n
    return int(np.argmax(~per_index))


def match_identity(parts, target):
    for p in parts:
        if p.shape != target.shape:
            return None
    got = Recombine.identity(parts)
    if got is not None and platform.allclose(got, target):
        return functools.partial(Recombine.identity)
    return None


def match_reduce(parts, target):
    for p in parts:
        if p.shape != target.shape:
            return None
    for op in (Reduction.SUM, Reduction.MAX, Reduction.MIN, Reduction.AVG):
        fn = functools.partial(Recombine.reduce, op=op)
        if platform.allclose(fn(parts), target):
            return fn
    return None


def match_concat(parts, target):
    """Try concat along the single differing dim; with `extend_space` also try
    block-cyclic interleave and halo overlap, and emit HaloHint when the
    mismatch pattern suggests the *inputs* need halo padding
    (reference combination.py:178-265)."""
    if len(target.shape) == 0:
        return None
    nparts = len(parts)
    pshape = parts[0].shape

    # exactly one dim may differ from the target, same dim on every part
    cat_dim = next((i for i in range(len(pshape)) if pshape[i] != target.shape[i]),
                   len(pshape) - 1)
    for p in parts:
        diff = [i for i in range(len(target.shape)) if p.shape[i] != target.shape[i]]
        if diff not in ([cat_dim], []):
            return None
        if diff == [] and p.shape[cat_dim] == target.shape[cat_dim] and nparts > 1:
            # parts same size as target on every dim: concat can't shrink them
            if pshape[cat_dim] * nparts != target.shape[cat_dim]:
                return None

    total = sum(p.shape[cat_dim] for p in parts)
    gap = total - target.shape[cat_dim]

    if gap == 0:
        fn = functools.partial(Recombine.concat, dim=cat_dim)
        if platform.allclose(fn(parts), target):
            return fn
        if edconfig.extend_space:
            # maybe the shards are block-cyclic: find how much of part 0
            # matches a plain first chunk of the target
            ref = platform.chunk(target, nparts, cat_dim)[0]
            prefix = _common_prefix_len(parts[0], ref, cat_dim)
            if prefix > 0 and pshape[cat_dim] % prefix == 0:
                block = pshape[cat_dim] // prefix
                fn = functools.partial(Recombine.concat, dim=cat_dim, block=block)
                if platform.allclose(fn(parts), target):
                    return fn
            # mostly-matching prefix: input halo padding may fix the tail
            if prefix > pshape[cat_dim] // 2:
                return HaloHint(pshape[cat_dim] - prefix, cat_dim)
        return None

    if not edconfig.extend_space:
        return None

    # parts overlap: neighbouring shards share `halo` summed elements
    if gap > 0 and nparts > 1 and gap % (nparts - 1) == 0:
        halo = gap // (nparts - 1)
        if halo < total // nparts:
            fn = functools.partial(Recombine.concat, dim=cat_dim, halo=halo)
            got = fn(parts)
            if got.shape == target.shape and platform.allclose(got, target):
                return fn

    # parts overhang: drop |halo| elements from BOTH sides of each of the
    # nparts-1 seams, so gap = 2*|halo|*(nparts-1)
    if gap > 0 and nparts > 1 and gap % (2 * (nparts - 1)) == 0:
        halo = -(gap // (2 * (nparts - 1)))
        if -halo < total // (2 * nparts):
            fn = functools.partial(Recombine.concat, dim=cat_dim, halo=halo)
            got = fn(parts)
            if got.shape == target.shape and platform.allclose(got, target):
                return fn

    # parts too small (valid convolution): ask for input halo padding; the
    # hinted width is positive (|gap| split over seams, half per side)
    if gap < 0 and nparts > 1 and gap % (nparts - 1) == 0:
        width = (-gap // (nparts - 1)) // 2
        if width < total // nparts:
            return HaloHint(max(width, 1), cat_dim)
    return None


def _aux_equal(a, b) -> bool:
    try:
        return bool(a == b)
    except Exception:
        try:
            import numpy as np

            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        except Exception:
            return False


_MATCHERS = (match_identity, match_reduce, match_concat)


def _match_single(parts, target):
    for p in parts:
        if len(p.shape) != len(target.shape):
            return None
    for matcher in _MATCHERS:
        fn = matcher(parts, target)
        if fn is not None:
            return fn  # may be a HaloHint
    return None


def match_recombine(sharded_outputs, global_output):
    """Match recombination for a (possibly multi-output) op execution.

    `sharded_outputs` is a list over shards; each element mirrors the structure
    of `global_output` (a tensor, or tuple/list of tensors and aux values).
    Returns a recombine fn, a list of them (multi-output), a HaloHint, or None.
    Reference: combination.py:283-310.
    """
    if isinstance(global_output, platform.Tensor):
        return _match_single(sharded_outputs, global_output)

    if isinstance(global_output, (tuple, list)):
        lens = [len(s) for s in sharded_outputs]
        if not lens or min(lens) != max(lens) or lens[0] != len(global_output):
            return None
        fns = []
        for i, glob in enumerate(global_output):
            if isinstance(glob, platform.Tensor):
                fn = _match_single([s[i] for s in sharded_outputs], glob)
                if fn is None:
                    return None
                if isinstance(fn, HaloHint):
                    fn.out_idx = i
                    return fn
                fns.append(fn)
            else:
                # non-tensor outputs must agree across shards; comparison must
                # never raise (array-likes that aren't the backend Tensor)
                for s in sharded_outputs:
                    if not _aux_equal(glob, s[i]):
                        return None
        return fns if fns else None
    return None
