"""Framework-neutral ShardCombine core (reference: easydist/metashard/).

The conceptual heart of the framework: discover per-operator SPMD sharding
rules by *executing* the op with sharded inputs and checking whether the
sharded outputs recombine into the global output.
"""

from .annotation import DimSharding, ShardSpace, HaloSpec  # noqa: F401
from .combination import Recombine, Reduction, match_recombine, HaloHint  # noqa: F401
from .metaop import MetaOp  # noqa: F401
from .view_propagation import view_rule, view_rule_for_space  # noqa: F401
