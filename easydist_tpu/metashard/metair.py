"""MetaIR: the framework-neutral SPMD strategy IR and dataflow graph.

Discovery results (ShardSpace + recombine fns per op) are lowered into
per-node strategy pools over the placement vocabulary

    R          replicate on the mesh axis
    S(dim)     shard tensor dim `dim` across the mesh axis
    P(red)     partial values that recombine by `red` (pending all_reduce)

The solver consumes a `MetaGraph` of `MetaNode`s coarsened into
`MetaNodeCluster`s whose intra-cluster strategies are sync-free (chosen by
back-propagating the cluster output node's strategies through its cone).

Reference semantics: easydist/metashard/metair.py (SPMD :29, VarSPMDStrategy
:63, NodeSPMDStrategy :131, strategy-pool construction :376-481, cone
clustering :842-917, liveness :818-840).  The IR here is a fresh design: one
`Placement` per mesh axis, ND strategies assembled by the frontend after the
per-axis solves.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .annotation import ShardSpace
from .combination import Recombine, Reduction

logger = logging.getLogger(__name__)

_DTYPE_BYTES = {
    "float64": 8, "float32": 4, "float16": 2, "bfloat16": 2,
    "int64": 8, "int32": 4, "int16": 2, "int8": 1, "uint8": 1,
    "uint32": 4, "uint64": 8, "bool": 1, "complex64": 8, "complex128": 16,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}


# --------------------------------------------------------------- placements

@dataclass(frozen=True, eq=False)
class Placement:
    """Placement of one tensor along ONE mesh axis."""

    kind: str  # "R" | "S" | "P"
    dim: int = -1  # tensor dim for S
    reduction: Optional[Reduction] = None  # for P

    def _key(self):
        # canonical identity: dim only matters for S, reduction only for P
        # (an R built with a stray dim is still just R)
        return (self.kind,
                self.dim if self.kind == "S" else -1,
                self.reduction if self.kind == "P" else None)

    def __eq__(self, other) -> bool:
        return isinstance(other, Placement) and self._key() == other._key()

    def __hash__(self) -> int:
        return hash(self._key())

    @staticmethod
    def replicate() -> "Placement":
        return Placement("R")

    @staticmethod
    def shard(dim: int) -> "Placement":
        return Placement("S", dim=dim)

    @staticmethod
    def partial(reduction: Reduction = Reduction.SUM) -> "Placement":
        return Placement("P", reduction=reduction)

    def is_replicate(self) -> bool:
        return self.kind == "R"

    def is_shard(self) -> bool:
        return self.kind == "S"

    def is_partial(self) -> bool:
        return self.kind == "P"

    def __repr__(self) -> str:
        if self.kind == "S":
            return f"S({self.dim})"
        if self.kind == "P":
            return f"P({self.reduction.value})"
        return "R"


class NodeStrategy:
    """One SPMD strategy of a node on one mesh axis: a Placement per graph
    invar and per outvar (reference NodeSPMDStrategy, metair.py:131)."""

    def __init__(self, in_placements: Sequence[Optional[Placement]],
                 out_placements: Sequence[Optional[Placement]]):
        self.in_placements = list(in_placements)
        self.out_placements = list(out_placements)
        # seconds of communication INSIDE the op under this strategy, priced
        # linearly by the solver (composite ops — a TP-sharded scan body pays
        # its per-iteration psums here; plain ops leave it 0)
        self.intrinsic_cost: float = 0.0
        # absolute compute seconds under this strategy (composite ops price
        # their body per-op: a strategy sharding only a trivial input must
        # not earn the whole body's 1/n discount); None -> the solver's
        # any-S factor heuristic
        self.compute_cost: Optional[float] = None

    def is_all_replicate(self) -> bool:
        return all(p is None or p.is_replicate() for p in self.out_placements)

    def __repr__(self) -> str:
        return f"NodeStrategy(in={self.in_placements}, out={self.out_placements})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, NodeStrategy)
                and self.in_placements == other.in_placements
                and self.out_placements == other.out_placements)


# ------------------------------------------------------------------- graph

class MetaVar:

    def __init__(self, name: str, shape: Tuple[int, ...], dtype: str):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.producer: Optional[MetaNode] = None
        self.producer_idx: int = -1
        self.consumers: List[Tuple[MetaNode, int]] = []  # (node, invar_idx)

    def size_bytes(self) -> float:
        n = math.prod(self.shape) if self.shape else 1
        return _DTYPE_BYTES.get(self.dtype, 4) * n

    def __repr__(self) -> str:
        return f"{self.name}({self.dtype}{list(self.shape)})"


class MetaNode:
    """One operator (or graph input placeholder) in the dataflow graph.

    `space`/`recombines` hold the ShardCombine discovery result.  The rows of
    `space` cover the op's *tensor arguments*; `arg_rows` maps each graph
    invar to its row index (non-Var tensor literals get rows too but no graph
    edge).  Placeholders have no invars; their single outvar's strategies come
    from their own space (reference is_placeholder handling, metair.py:361).
    """

    _uid = 0

    def __init__(self, name: str, op_key: str, invars: List[MetaVar],
                 outvars: List[Optional[MetaVar]],
                 space: Optional[ShardSpace] = None,
                 recombines: Optional[Dict[int, object]] = None,
                 arg_rows: Optional[List[int]] = None,
                 is_input: bool = False, sig: Optional[str] = None):
        MetaNode._uid += 1
        self.uid = MetaNode._uid
        self.name = name
        self.op_key = op_key
        # full op signature (primitive + params + shapes/dtypes) — the
        # PerfDB key for measured per-op runtimes (reference
        # runtime_prof.py keys ops the same way)
        self.sig = sig
        self.invars = invars
        self.outvars = outvars
        self.space = space
        self.recombines = recombines or {}
        # whole-node strategies that bypass the group table (composite ops:
        # a scan's candidate assignments overlap on dims, which one-group-
        # per-cell tables cannot encode).  List of NodeStrategy.
        self.explicit_strategies: Optional[List[NodeStrategy]] = None
        # exact MACs recorded by the bridge for dot/conv eqns (shape-only
        # recovery of the contraction length is ambiguous)
        self.flops: Optional[float] = None
        # full (unsharded) compute seconds when the node hides more work
        # than its output bytes show (scan: length x body); None -> the
        # solver's HBM byte proxy
        self.compute_proxy: Optional[float] = None
        self.arg_rows = arg_rows if arg_rows is not None else list(range(len(invars)))
        self.is_input = is_input
        self.cluster_id = -1
        self._pool_cache: Optional[List[NodeStrategy]] = None
        # user-pinned strategy for this solve axis (fix_sharding): when
        # set, the pool is exactly [pinned] — the solver prices neighbors
        # against the pin instead of fighting it at emission
        self.pinned: Optional[NodeStrategy] = None

        for idx, v in enumerate(invars):
            if v is not None:
                v.consumers.append((self, idx))
        for idx, v in enumerate(outvars):
            if v is not None:
                v.producer = self
                v.producer_idx = idx

    # ------------------------------------------------------ strategy pool

    def _recombine_placement(self, fn) -> Placement:
        name = fn.func.__name__ if hasattr(fn, "func") else fn.__name__
        kw = getattr(fn, "keywords", {})
        if name == "identity":
            return Placement.replicate()
        if name == "concat":
            return Placement.shard(kw.get("dim", 0))
        if name == "reduce":
            return Placement.partial(kw.get("op", Reduction.SUM))
        raise RuntimeError(f"unknown recombine fn {name}")

    def _strategy_for_group(self, group: int) -> Optional[NodeStrategy]:
        fns = self.recombines.get(group)
        if fns is None:
            return None
        if not isinstance(fns, (list, tuple)):
            fns = [fns]

        if self.is_input:
            in_placements = []
        else:
            in_placements = []
            for row_idx in self.arg_rows:
                if row_idx < 0 or self.space is None or row_idx >= len(self.space):
                    in_placements.append(Placement.replicate())
                    continue
                dim = self.space.group_dim(row_idx, group)
                in_placements.append(Placement.shard(dim) if dim is not None
                                     else Placement.replicate())

        out_placements: List[Optional[Placement]] = []
        fn_iter = iter(fns)
        for v in self.outvars:
            if v is None:
                out_placements.append(None)
            else:
                try:
                    out_placements.append(self._recombine_placement(next(fn_iter)))
                except StopIteration:
                    out_placements.append(Placement.replicate())
        return NodeStrategy(in_placements, out_placements)

    def replicate_strategy(self) -> NodeStrategy:
        n_in = 0 if self.is_input else len(self.invars)
        return NodeStrategy([Placement.replicate()] * n_in,
                            [Placement.replicate() if v is not None else None
                             for v in self.outvars])

    def strategy_pool(self, axis_size: int,
                      exclude: Sequence[NodeStrategy] = ()) -> List[NodeStrategy]:
        """All valid 1D strategies on a mesh axis of `axis_size` devices:
        one per discovered shard group whose sharded dims divide evenly,
        minus `exclude` (strategies already chosen on previous mesh axes —
        reference metair.py:393-430), plus replicate as fallback."""
        if self.pinned is not None:
            return [self.pinned]
        if self._pool_cache is None:
            if self.explicit_strategies is not None:
                self._pool_cache = list(self.explicit_strategies)
            else:
                pool = []
                for group in sorted(self.recombines):
                    s = self._strategy_for_group(group)
                    if s is not None:
                        pool.append(s)
                self._pool_cache = pool

        def divisible(s: NodeStrategy) -> bool:
            vars_for_in = self.outvars if self.is_input else self.invars
            placements = s.out_placements if self.is_input else s.in_placements
            for v, p in zip(vars_for_in, placements):
                if v is not None and p is not None and p.is_shard():
                    if v.shape[p.dim] % axis_size != 0:
                        return False
            for v, p in zip(self.outvars, s.out_placements):
                if v is not None and p is not None and p.is_shard():
                    if v.shape[p.dim] % axis_size != 0:
                        return False
            return True

        pool = [s for s in self._pool_cache
                if divisible(s) and all(s != e for e in exclude)]
        # Every op (placeholders AND compute) may replicate — the reference
        # forces shards (its replicate branch is commented out,
        # metair.py:441-443), which mis-prices DP weight replication.  The
        # zero-communication all-replicate degeneracy this would create
        # under a comm-only objective is priced away by the solver's
        # compute-redundancy cost (replicated compute runs full-size on
        # every device; sharded runs 1/n — see SpmdSolver._collect_edges).
        rep = self.replicate_strategy()
        if all(s != rep for s in pool) and all(rep != e for e in exclude):
            pool.append(rep)
        if not pool:
            pool = [rep]
        return pool

    def __repr__(self) -> str:
        return f"MetaNode({self.name}: {self.op_key})"


# composites solved as their own cluster (see coarsen): control flow and
# jax.checkpoint regions — both carry explicit priced strategies whose
# many-input boundaries a cone back-build would sync-free-match away
_SOLO_CLUSTER_OPS = {"scan", "while", "cond", "remat2", "remat", "checkpoint"}


# ---------------------------------------------------------------- clusters

class MetaNodeCluster:
    """A group of nodes solved as one unit.  Its strategy list is derived by
    taking each strategy of the cluster's output node and back-propagating
    matching (sync-free) strategies to every interior node
    (reference back_build_strategy, metair.py:659-699)."""

    def __init__(self, cid: int):
        self.cid = cid
        self.nodes: Dict[int, MetaNode] = {}
        self.output_node: Optional[MetaNode] = None
        # per cluster strategy: {node_uid: (pool_idx, NodeStrategy)}
        self.strategies: List[Dict[int, Tuple[int, NodeStrategy]]] = []

    def add(self, node: MetaNode):
        self.nodes[node.uid] = node
        node.cluster_id = self.cid

    # bound on sync-free assignments enumerated per output-pool entry: the
    # branching is tiny in practice (cones are near-trees, 1-3 matching
    # producer strategies per edge) but a pathological cluster must not
    # blow up the ILP
    _BACK_BUILD_CAP = 16
    # bound on total DFS expansions per output-pool entry: a branchy
    # cluster whose combinations mostly DEAD-END never fills `results`,
    # so the result cap alone would still let the tree search go
    # multiplicative (k matches/edge over n nodes)
    _BACK_BUILD_STEPS = 512

    def _back_build_all(self, pending, chosen, axis_size, exclude_map,
                        results, steps) -> None:
        """Enumerate every sync-free intra-cluster assignment consistent
        with the already-`chosen` strategies.  `pending` holds (node,
        strategy) pairs whose in-cluster producers still need covering;
        `steps` is a single-element work counter shared across the DFS.
        Enumerating ALL matches (not just the first) matters: a P-chain
        cluster has both a "create P mid-chain" and a "P rides the whole
        chain" assignment for the same output placement, and first-match
        back-build shadows the second."""
        steps[0] += 1
        if len(results) >= self._BACK_BUILD_CAP \
                or steps[0] > self._BACK_BUILD_STEPS:
            return
        while pending:
            node, strategy = pending[-1]
            edge = None
            for invar_idx, invar in enumerate(node.invars):
                if invar is None or invar.producer is None:
                    continue
                up = invar.producer
                if up.uid not in self.nodes:
                    continue
                want = strategy.in_placements[invar_idx]
                if up.uid in chosen:
                    # a second in-cluster consumer: sync-free requires the
                    # already-chosen producer strategy to serve it too
                    have = chosen[up.uid][1].out_placements[
                        invar.producer_idx]
                    if have != want:
                        return  # dead end
                    continue
                edge = (invar_idx, invar, up)
                break
            if edge is None:
                pending = pending[:-1]
                continue
            invar_idx, invar, up = edge
            want = strategy.in_placements[invar_idx]
            up_pool = up.strategy_pool(axis_size, exclude_map(up))
            for i, s in enumerate(up_pool):
                if s.out_placements[invar.producer_idx] != want:
                    continue
                nxt = dict(chosen)
                nxt[up.uid] = (i, s)
                self._back_build_all(pending + [(up, s)], nxt, axis_size,
                                     exclude_map, results, steps)
                if len(results) >= self._BACK_BUILD_CAP \
                        or steps[0] > self._BACK_BUILD_STEPS:
                    return
            return  # every branch of this edge explored (or none matched)
        results.append(chosen)

    def finalize(self, axis_size: int, exclude_map) -> None:
        # output node: the unique node with a var consumed outside the cluster
        # (or a graph output).  Root selection keeps this unique for cones;
        # if it still isn't (defensive), take the topologically-last external
        # node — back_build then falls back to all-replicate if needed.
        external_nodes = []
        for node in self.nodes.values():
            for v in node.outvars:
                if v is None:
                    continue
                external = not v.consumers or any(
                    c.uid not in self.nodes for c, _ in v.consumers)
                if external:
                    external_nodes.append(node)
                    break
        if not external_nodes:
            out_node = next(iter(self.nodes.values()))
        else:
            if len(external_nodes) > 1:
                logger.debug("cluster %d has %d external nodes; using the "
                             "last one", self.cid, len(external_nodes))
            out_node = max(external_nodes, key=lambda n: n.uid)
        self.output_node = out_node

        self.strategies = []
        seen = set()
        for idx, s in enumerate(out_node.strategy_pool(axis_size,
                                                       exclude_map(out_node))):
            results: List[Dict[int, Tuple[int, NodeStrategy]]] = []
            self._back_build_all([(out_node, s)],
                                 {out_node.uid: (idx, s)}, axis_size,
                                 exclude_map, results, steps=[0])
            for chosen in results:
                if len(chosen) != len(self.nodes):
                    logger.debug("cluster %d: strategy %d left nodes "
                                 "unassigned", self.cid, idx)
                    continue
                key = tuple(sorted((uid, i) for uid, (i, _) in chosen.items()))
                if key in seen:
                    continue
                seen.add(key)
                self.strategies.append(chosen)
        if not self.strategies:
            # fall back to all-replicate so the solver always has a choice
            chosen = {n.uid: (-1, n.replicate_strategy())
                      for n in self.nodes.values()}
            self.strategies.append(chosen)

    def strategy_count(self) -> int:
        return len(self.strategies)

    def node_strategy(self, node_uid: int, strategy_idx: int) -> NodeStrategy:
        return self.strategies[strategy_idx][node_uid][1]


class MetaGraph:

    def __init__(self, name: str = "graph"):
        self.name = name
        self.inputs: List[MetaNode] = []  # placeholder nodes
        self.ops: List[MetaNode] = []  # topological order, excludes inputs
        self.outputs: List[MetaVar] = []
        self.clusters: List[MetaNodeCluster] = []
        # updated-state outvar -> input placeholder node (train-step param/opt
        # threading; reference state_io_map, metair.py:793)
        self.state_io: Dict[str, MetaNode] = {}

    def add_input(self, node: MetaNode):
        self.inputs.append(node)

    def add_op(self, node: MetaNode):
        self.ops.append(node)

    def all_nodes(self) -> List[MetaNode]:
        return self.inputs + self.ops

    # ------------------------------------------------------------ liveness

    def liveness(self) -> List[List[MetaVar]]:
        """Live variable set before each op (reference metair.py:818-840)."""
        live: Dict[str, MetaVar] = {v.name: v for v in self.outputs}
        timeline: List[List[MetaVar]] = []
        for op in reversed(self.ops):
            for v in op.invars:
                if v is not None:
                    live[v.name] = v
            for v in op.outvars:
                if v is not None:
                    live[v.name] = v
            timeline.insert(0, list(live.values()))
            for v in op.outvars:
                if v is not None:
                    live.pop(v.name, None)
        return timeline

    # ---------------------------------------------------------- clustering

    def _cone_roots(self) -> List[MetaNode]:
        """A node roots a cone unless it has exactly one consumer, exactly one
        produced input, and does not shrink its input (reference
        find_cone_roots, metair.py:852-892)."""
        roots = []
        for node in self.ops:
            if node.op_key in _SOLO_CLUSTER_OPS:
                # composites must never be grown into a downstream cone:
                # back-build would sync-free-match their many-input boundary
                # and silently drop strategies (a single-outvar scan passes
                # every other root test)
                roots.append(node)
                continue
            # externally-visible edges: every consumer, plus each dangling /
            # graph-output var (no consumers).  A cone interior node must
            # have exactly one — multi-output prims like scan whose extra
            # outputs dangle would otherwise give a cone two output nodes.
            external = 0
            for v in node.outvars:
                if v is None:
                    continue
                external += len(v.consumers) if v.consumers else 1
            if external != 1:
                roots.append(node)
                continue
            produced_ins = [v for v in node.invars
                            if v is not None and v.producer is not None
                            and not v.producer.is_input]
            if len(produced_ins) > 1:
                roots.append(node)
                continue
            if len(produced_ins) == 0:
                continue  # interior leaf of some cone
            out_sizes = [v.size_bytes() for v in node.outvars if v is not None]
            if out_sizes and out_sizes[0] < produced_ins[0].size_bytes():
                roots.append(node)
        return roots

    def coarsen(self, axis_size: int, level: int = 1,
                exclude_map=lambda node: ()) -> None:
        """Build clusters and their sync-free strategy lists.

        level 0: one node per cluster; level >=1: cone clusters.
        `exclude_map(node)` returns strategies banned for that node (already
        chosen on previous mesh axes)."""
        self.clusters = []
        for node in self.inputs:
            c = MetaNodeCluster(len(self.clusters))
            c.add(node)
            c.finalize(axis_size, exclude_map)
            self.clusters.append(c)

        if level == 0:
            for node in self.ops:
                c = MetaNodeCluster(len(self.clusters))
                c.add(node)
                c.finalize(axis_size, exclude_map)
                self.clusters.append(c)
            return

        roots = self._cone_roots()
        root_ids = {n.uid for n in roots}
        visited = set()

        def grow(node: MetaNode, cluster: MetaNodeCluster):
            cluster.add(node)
            visited.add(node.uid)
            for v in node.invars:
                if v is not None and v.producer is not None \
                        and not v.producer.is_input \
                        and v.producer.uid not in root_ids \
                        and v.producer.uid not in visited:
                    grow(v.producer, cluster)

        for root in roots:
            c = MetaNodeCluster(len(self.clusters))
            if root.op_key in _SOLO_CLUSTER_OPS:
                # composite ops price their internals via intrinsic_cost and
                # have many-input boundaries; absorbing producers into their
                # cone would DROP any strategy a producer can't serve
                # sync-free (R->S is a free slice when priced as an edge)
                c.add(root)
                visited.add(root.uid)
            else:
                grow(root, c)
            c.finalize(axis_size, exclude_map)
            self.clusters.append(c)

        # any op not reached (cycles can't happen; dangling chains can)
        for node in self.ops:
            if node.uid not in visited:
                c = MetaNodeCluster(len(self.clusters))
                c.add(node)
                c.finalize(axis_size, exclude_map)
                self.clusters.append(c)

    def __repr__(self) -> str:
        lines = [f"MetaGraph({self.name}): {len(self.inputs)} inputs, "
                 f"{len(self.ops)} ops, {len(self.outputs)} outputs"]
        for op in self.ops:
            lines.append(f"  {op.outvars} <- {op.op_key} <- {op.invars}")
        return "\n".join(lines)
