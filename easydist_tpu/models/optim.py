"""Minimal pure-jax optimizers for traced train steps.

Hand-written (rather than optax) so the optimizer update is plain jaxpr
arithmetic the discovery engine shards like any other op — the analog of the
reference tracing `optimizer.step()` into the same fx graph
(torch/compile.py:52-83).

Hyperparameters (`lr`, `weight_decay`) accept either a scalar or a pytree
matching `params` — per-parameter-group settings (torch.optim param_groups,
reference compile.py:52-67 traces them natively) become per-leaf trees.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _hyper_tree(val, params):
    """Broadcast a scalar hyperparameter to every param leaf; pass trees
    through (must match the params structure)."""
    if isinstance(val, (int, float)) or getattr(val, "ndim", None) == 0:
        return jax.tree_util.tree_map(lambda _: val, params)
    return val


def adam_init(params):
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    return {"mu": zeros,
            "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32)}


def adam_update(params, grads, state, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, decoupled=False):
    """torch.optim.Adam semantics; `decoupled=True` gives AdamW (weight
    decay applied directly to the parameter, not folded into the grad).
    `b1`/`b2` accept scalars or per-leaf trees (per-group betas)."""
    lr_t = _hyper_tree(lr, params)
    wd_t = _hyper_tree(weight_decay, params)
    b1_t = _hyper_tree(b1, params)
    b2_t = _hyper_tree(b2, params)
    if not decoupled:
        grads = jax.tree_util.tree_map(lambda g, p, wd: g + wd * p,
                                       grads, params, wd_t)
    count = state["count"] + 1
    fcount = count.astype(jnp.float32)
    mu = jax.tree_util.tree_map(lambda m, g, b1_: b1_ * m + (1 - b1_) * g,
                                state["mu"], grads, b1_t)
    nu = jax.tree_util.tree_map(lambda v, g, b2_: b2_ * v + (1 - b2_) * g * g,
                                state["nu"], grads, b2_t)
    if decoupled:
        new_params = jax.tree_util.tree_map(
            lambda p, m, v, lr_, wd_, b1_, b2_: p - lr_ * (
                (m / (1 - b1_ ** fcount))
                / (jnp.sqrt(v / (1 - b2_ ** fcount)) + eps) + wd_ * p),
            params, mu, nu, lr_t, wd_t, b1_t, b2_t)
    else:
        new_params = jax.tree_util.tree_map(
            lambda p, m, v, lr_, b1_, b2_: p - lr_ * (m / (1 - b1_ ** fcount))
            / (jnp.sqrt(v / (1 - b2_ ** fcount)) + eps),
            params, mu, nu, lr_t, b1_t, b2_t)
    return new_params, {"mu": mu, "nu": nu, "count": count}


def adamw_update(params, grads, state, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                 weight_decay=1e-2):
    return adam_update(params, grads, state, lr=lr, b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay, decoupled=True)


def rmsprop_init(params, momentum=0.0, centered=False):
    state = {"sq": jax.tree_util.tree_map(jnp.zeros_like, params)}
    if momentum:
        state["buf"] = jax.tree_util.tree_map(jnp.zeros_like, params)
    if centered:
        state["gavg"] = jax.tree_util.tree_map(jnp.zeros_like, params)
    return state


def rmsprop_update(params, grads, state, lr=1e-2, alpha=0.99, eps=1e-8,
                   weight_decay=0.0, momentum=0.0, centered=False):
    """torch.optim.RMSprop semantics (square-avg EMA; optional heavy-ball
    momentum on the preconditioned grad; optional centered variant)."""
    lr_t = _hyper_tree(lr, params)
    wd_t = _hyper_tree(weight_decay, params)
    grads = jax.tree_util.tree_map(lambda g, p, wd: g + wd * p,
                                   grads, params, wd_t)
    sq = jax.tree_util.tree_map(lambda s, g: alpha * s + (1 - alpha) * g * g,
                                state["sq"], grads)
    new_state = {"sq": sq}
    if centered:
        gavg = jax.tree_util.tree_map(
            lambda a, g: alpha * a + (1 - alpha) * g, state["gavg"], grads)
        new_state["gavg"] = gavg
        denom = jax.tree_util.tree_map(
            lambda s, a: jnp.sqrt(s - a * a) + eps, sq, gavg)
    else:
        denom = jax.tree_util.tree_map(lambda s: jnp.sqrt(s) + eps, sq)
    if momentum:
        buf = jax.tree_util.tree_map(lambda b, g, d: momentum * b + g / d,
                                     state["buf"], grads, denom)
        new_state["buf"] = buf
        new_params = jax.tree_util.tree_map(lambda p, b, lr_: p - lr_ * b,
                                            params, buf, lr_t)
    else:
        new_params = jax.tree_util.tree_map(
            lambda p, g, d, lr_: p - lr_ * g / d, params, grads, denom, lr_t)
    return new_params, new_state


def adagrad_init(params, initial_accumulator_value=0.0):
    return {"sum": jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, initial_accumulator_value),
                params),
            "count": jnp.zeros((), jnp.int32)}


def adagrad_update(params, grads, state, lr=1e-2, lr_decay=0.0, eps=1e-10,
                   weight_decay=0.0):
    """torch.optim.Adagrad semantics (accumulated squared grads; lr decayed
    by 1/(1 + step*lr_decay) with step counted from 0)."""
    lr_t = _hyper_tree(lr, params)
    wd_t = _hyper_tree(weight_decay, params)
    grads = jax.tree_util.tree_map(lambda g, p, wd: g + wd * p,
                                   grads, params, wd_t)
    acc = jax.tree_util.tree_map(lambda s, g: s + g * g, state["sum"], grads)
    decay = 1.0 + state["count"].astype(jnp.float32) * lr_decay
    new_params = jax.tree_util.tree_map(
        lambda p, g, s, lr_: p - (lr_ / decay) * g / (jnp.sqrt(s) + eps),
        params, grads, acc, lr_t)
    return new_params, {"sum": acc, "count": state["count"] + 1}


def sgd_init(params):
    """Momentum buffers (torch initializes the buffer to the first grad —
    equivalent to momentum * 0 + g)."""
    return {"buf": jax.tree_util.tree_map(jnp.zeros_like, params)}


def sgd_update(params, grads, lr=1e-2, momentum=0.0, nesterov=False,
               weight_decay=0.0, state=None):
    """torch.optim.SGD semantics.  Stateless (returns new params) when
    `state` is None and momentum is 0; with momentum pass `state` from
    `sgd_init` and receive `(new_params, new_state)`."""
    lr_t = _hyper_tree(lr, params)
    wd_t = _hyper_tree(weight_decay, params)
    grads = jax.tree_util.tree_map(lambda g, p, wd: g + wd * p,
                                   grads, params, wd_t)
    if momentum:
        if state is None:
            raise ValueError("sgd momentum requires state from sgd_init()")
        buf = jax.tree_util.tree_map(lambda b, g: momentum * b + g,
                                     state["buf"], grads)
        if nesterov:
            grads = jax.tree_util.tree_map(lambda g, b: g + momentum * b,
                                           grads, buf)
        else:
            grads = buf
        new_params = jax.tree_util.tree_map(lambda p, g, lr_: p - lr_ * g,
                                            params, grads, lr_t)
        return new_params, {"buf": buf}
    new_params = jax.tree_util.tree_map(lambda p, g, lr_: p - lr_ * g,
                                        params, grads, lr_t)
    if state is not None:
        return new_params, state
    return new_params
