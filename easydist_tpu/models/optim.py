"""Minimal pure-jax optimizers for traced train steps.

Hand-written (rather than optax) so the optimizer update is plain jaxpr
arithmetic the discovery engine shards like any other op — the analog of the
reference tracing `optimizer.step()` into the same fx graph
(torch/compile.py:52-83).

Hyperparameters (`lr`, `weight_decay`) accept either a scalar or a pytree
matching `params` — per-parameter-group settings (torch.optim param_groups,
reference compile.py:52-67 traces them natively) become per-leaf trees.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _hyper_tree(val, params):
    """Broadcast a scalar hyperparameter to every param leaf; pass trees
    through (must match the params structure)."""
    if isinstance(val, (int, float)) or getattr(val, "ndim", None) == 0:
        return jax.tree_util.tree_map(lambda _: val, params)
    return val


def adam_init(params):
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    return {"mu": zeros,
            "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32)}


def adam_update(params, grads, state, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, decoupled=False):
    """torch.optim.Adam semantics; `decoupled=True` gives AdamW (weight
    decay applied directly to the parameter, not folded into the grad)."""
    lr_t = _hyper_tree(lr, params)
    wd_t = _hyper_tree(weight_decay, params)
    if not decoupled:
        grads = jax.tree_util.tree_map(lambda g, p, wd: g + wd * p,
                                       grads, params, wd_t)
    count = state["count"] + 1
    mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                state["mu"], grads)
    nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                state["nu"], grads)
    c1 = 1 - b1 ** count.astype(jnp.float32)
    c2 = 1 - b2 ** count.astype(jnp.float32)
    if decoupled:
        new_params = jax.tree_util.tree_map(
            lambda p, m, v, lr_, wd_: p - lr_ * (
                (m / c1) / (jnp.sqrt(v / c2) + eps) + wd_ * p),
            params, mu, nu, lr_t, wd_t)
    else:
        new_params = jax.tree_util.tree_map(
            lambda p, m, v, lr_: p - lr_ * (m / c1) / (jnp.sqrt(v / c2) + eps),
            params, mu, nu, lr_t)
    return new_params, {"mu": mu, "nu": nu, "count": count}


def adamw_update(params, grads, state, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                 weight_decay=1e-2):
    return adam_update(params, grads, state, lr=lr, b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay, decoupled=True)


def sgd_init(params):
    """Momentum buffers (torch initializes the buffer to the first grad —
    equivalent to momentum * 0 + g)."""
    return {"buf": jax.tree_util.tree_map(jnp.zeros_like, params)}


def sgd_update(params, grads, lr=1e-2, momentum=0.0, nesterov=False,
               weight_decay=0.0, state=None):
    """torch.optim.SGD semantics.  Stateless (returns new params) when
    `state` is None and momentum is 0; with momentum pass `state` from
    `sgd_init` and receive `(new_params, new_state)`."""
    lr_t = _hyper_tree(lr, params)
    wd_t = _hyper_tree(weight_decay, params)
    grads = jax.tree_util.tree_map(lambda g, p, wd: g + wd * p,
                                   grads, params, wd_t)
    if momentum:
        if state is None:
            raise ValueError("sgd momentum requires state from sgd_init()")
        buf = jax.tree_util.tree_map(lambda b, g: momentum * b + g,
                                     state["buf"], grads)
        if nesterov:
            grads = jax.tree_util.tree_map(lambda g, b: g + momentum * b,
                                           grads, buf)
        else:
            grads = buf
        new_params = jax.tree_util.tree_map(lambda p, g, lr_: p - lr_ * g,
                                            params, grads, lr_t)
        return new_params, {"buf": buf}
    new_params = jax.tree_util.tree_map(lambda p, g, lr_: p - lr_ * g,
                                        params, grads, lr_t)
    if state is not None:
        return new_params, state
    return new_params
