"""Minimal pure-jax optimizers for traced train steps.

Hand-written (rather than optax) so the optimizer update is plain jaxpr
arithmetic the discovery engine shards like any other op — the analog of the
reference tracing `optimizer.step()` into the same fx graph
(torch/compile.py:52-83)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def adam_init(params):
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    return {"mu": zeros,
            "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32)}


def adam_update(params, grads, state, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0):
    if weight_decay:
        # torch.optim.Adam semantics: L2 folded into the gradient
        grads = jax.tree_util.tree_map(lambda g, p: g + weight_decay * p,
                                       grads, params)
    count = state["count"] + 1
    mu = jax.tree_util.tree_map(lambda m, g: b1 * m + (1 - b1) * g,
                                state["mu"], grads)
    nu = jax.tree_util.tree_map(lambda v, g: b2 * v + (1 - b2) * g * g,
                                state["nu"], grads)
    c1 = 1 - b1 ** count.astype(jnp.float32)
    c2 = 1 - b2 ** count.astype(jnp.float32)
    new_params = jax.tree_util.tree_map(
        lambda p, m, v: p - lr * (m / c1) / (jnp.sqrt(v / c2) + eps),
        params, mu, nu)
    return new_params, {"mu": mu, "nu": nu, "count": count}


def sgd_update(params, grads, lr=1e-2):
    return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
