"""ResNet (18-ish, configurable widths) in pure jax, NHWC layout.

Reference benchmark model: wide-ResNet50 bs128 (benchmark/bench_case.py:16-20).
Uses GroupNorm instead of BatchNorm: batch-stat sync across shards is exactly
the cross-replica dependence auto-SPMD should not have to special-case (the
reference burns a whole DTensor prop-rule section on batch_norm variants,
spmd_prop_rule.py); GroupNorm is the standard data-parallel-clean choice.

Architecture statics (strides, shortcut flags) live in a separate `arch`
structure, NOT in the params pytree, so grads/optimizer tree_maps only see
float leaves."""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp

from .optim import sgd_update


def _conv_init(key, kh, kw, c_in, c_out):
    fan_in = kh * kw * c_in
    return jax.random.normal(key, (kh, kw, c_in, c_out)) * math.sqrt(2.0 / fan_in)


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _groupnorm(x, g, b, groups=8, eps=1e-5):
    n, h, w, c = x.shape
    xg = x.reshape(n, h, w, groups, c // groups)
    mu = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mu) * jax.lax.rsqrt(var + eps)
    return xg.reshape(n, h, w, c) * g + b


def resnet_init(key, widths=(16, 32, 64), blocks_per_stage=2, classes=10,
                in_channels=3) -> Tuple[Dict, List]:
    """Returns (params, arch): params is all-float pytree, arch is static."""
    keys = iter(jax.random.split(key, 256))
    params: Dict = {"stem": _conv_init(next(keys), 3, 3, in_channels, widths[0]),
                    "stages": [], "head": {}}
    arch: List[List[Dict]] = []
    c_in = widths[0]
    for c_out in widths:
        stage: List[Dict] = []
        stage_arch: List[Dict] = []
        for b in range(blocks_per_stage):
            stride = 2 if (b == 0 and c_out != widths[0]) else 1
            blk = {
                "conv1": _conv_init(next(keys), 3, 3, c_in, c_out),
                "gn1": {"g": jnp.ones((c_out,)), "b": jnp.zeros((c_out,))},
                "conv2": _conv_init(next(keys), 3, 3, c_out, c_out),
                "gn2": {"g": jnp.ones((c_out,)), "b": jnp.zeros((c_out,))},
            }
            has_short = stride != 1 or c_in != c_out
            if has_short:
                blk["short"] = _conv_init(next(keys), 1, 1, c_in, c_out)
            stage.append(blk)
            stage_arch.append({"stride": stride, "has_short": has_short})
            c_in = c_out
        params["stages"].append(stage)
        arch.append(stage_arch)
    params["head"] = {"w": jax.random.normal(next(keys), (c_in, classes))
                      / math.sqrt(c_in),
                      "b": jnp.zeros((classes,))}
    return params, arch


def resnet_apply(params, arch, x):
    x = _conv(x, params["stem"])
    for stage, stage_arch in zip(params["stages"], arch):
        for blk, meta in zip(stage, stage_arch):
            h = _conv(x, blk["conv1"], stride=meta["stride"])
            h = jax.nn.relu(_groupnorm(h, blk["gn1"]["g"], blk["gn1"]["b"]))
            h = _conv(h, blk["conv2"])
            h = _groupnorm(h, blk["gn2"]["g"], blk["gn2"]["b"])
            sc = x if not meta["has_short"] else _conv(x, blk["short"],
                                                      stride=meta["stride"])
            x = jax.nn.relu(h + sc)
    x = x.mean(axis=(1, 2))
    return x @ params["head"]["w"] + params["head"]["b"]


def make_resnet_train_step(arch, lr=1e-2):
    def train_step(params, x, labels):
        def loss_fn(p):
            logits = resnet_apply(p, arch, x)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return sgd_update(params, grads, lr=lr), loss

    return train_step
