"""Llama-style decoder: RMSNorm, rotary embeddings, SwiGLU, grouped-query
attention (BASELINE.json config: "Llama-2-7B pretrain, autoflow 2D (DPxTP)
plan").  Pure jax, bf16-ready, static shapes."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp

from .optim import adam_init, adam_update


@dataclass
class LlamaConfig:
    vocab: int = 32000
    seq: int = 2048
    dim: int = 4096
    heads: int = 32
    kv_heads: int = 32
    layers: int = 32
    ffn_dim: int = 11008
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab=128, seq=32, dim=32, heads=4, kv_heads=2, layers=2,
                    ffn_dim=64, dtype="float32")
        base.update(kw)
        return LlamaConfig(**base)


def llama_init(cfg: LlamaConfig, key) -> Dict:
    keys = jax.random.split(key, 1 + cfg.layers)
    hd = cfg.dim // cfg.heads
    params = {
        "wte": jax.random.normal(keys[0], (cfg.vocab, cfg.dim)) * 0.02,
        "blocks": [],
        "norm_f": jnp.ones((cfg.dim,)),
    }
    scale = 1.0 / math.sqrt(cfg.dim)
    for i in range(cfg.layers):
        bk = jax.random.split(keys[1 + i], 7)
        params["blocks"].append({
            "attn_norm": jnp.ones((cfg.dim,)),
            "wq": jax.random.normal(bk[0], (cfg.dim, cfg.heads * hd)) * scale,
            "wk": jax.random.normal(bk[1], (cfg.dim, cfg.kv_heads * hd)) * scale,
            "wv": jax.random.normal(bk[2], (cfg.dim, cfg.kv_heads * hd)) * scale,
            "wo": jax.random.normal(bk[3], (cfg.heads * hd, cfg.dim)) * scale,
            "ffn_norm": jnp.ones((cfg.dim,)),
            "w_gate": jax.random.normal(bk[4], (cfg.dim, cfg.ffn_dim)) * scale,
            "w_up": jax.random.normal(bk[5], (cfg.dim, cfg.ffn_dim)) * scale,
            "w_down": jax.random.normal(bk[6], (cfg.ffn_dim, cfg.dim))
                      * (1.0 / math.sqrt(cfg.ffn_dim)),
        })
    return params


def _rmsnorm(x, g, eps=1e-5):
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * g


def _rope(x, theta):
    """x: [b, h, t, d]; rotate pairs along d with position-dependent angles."""
    b, h, t, d = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    pos = jnp.arange(t, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]  # [t, d/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    return jnp.stack([r1, r2], axis=-1).reshape(b, h, t, d)


def _gqa_attention(x, blk, cfg: LlamaConfig, dtype):
    b, t, _ = x.shape
    hd = cfg.dim // cfg.heads
    rep = cfg.heads // cfg.kv_heads

    def heads(y, n):
        return y.reshape(b, t, n, hd).transpose(0, 2, 1, 3)

    q = heads(x @ blk["wq"].astype(dtype), cfg.heads)
    k = heads(x @ blk["wk"].astype(dtype), cfg.kv_heads)
    v = heads(x @ blk["wv"].astype(dtype), cfg.kv_heads)
    q = _rope(q.astype(jnp.float32), cfg.rope_theta).astype(dtype)
    k = _rope(k.astype(jnp.float32), cfg.rope_theta).astype(dtype)
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    att = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
    qi = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    att = jnp.where(ki <= qi, att, jnp.array(-1e9, att.dtype))
    att = jax.nn.softmax(att, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, cfg.heads * hd)
    return out @ blk["wo"].astype(dtype)


def llama_apply(params, cfg: LlamaConfig, tokens):
    dtype = jnp.dtype(cfg.dtype)
    x = params["wte"][tokens].astype(dtype)
    for blk in params["blocks"]:
        h = _rmsnorm(x, blk["attn_norm"]).astype(dtype)
        x = x + _gqa_attention(h, blk, cfg, dtype)
        h = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(h @ blk["w_gate"].astype(dtype)) \
            * (h @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    x = _rmsnorm(x, params["norm_f"])
    return x.astype(jnp.float32) @ params["wte"].T


# --------------------------------------------------------- KV-cache decode
#
# Same contract as models/gpt.py: `init_kv_cache` + `llama_prefill` +
# `llama_decode_step`, returning the updated cache functionally so the
# compiled step donates it.  The cache stores ROPED keys at kv_heads
# granularity (GQA: the repeat to full heads happens at attention time, so
# cache HBM scales with kv_heads, not heads).


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int, dtype=None):
    """Zeroed KV cache {"k", "v"}: [layers, batch, kv_heads, max_len,
    head_dim].  No position-table bound — RoPE extends to any max_len."""
    hd = cfg.dim // cfg.heads
    dt = jnp.dtype(cfg.dtype if dtype in (None, "auto") else dtype)
    shape = (cfg.layers, batch, cfg.kv_heads, max_len, hd)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _rope_at(x, pos, theta):
    """x: [b, n, d] single-position heads rotated at absolute positions
    `pos` (int32 [b]) — the decode-time form of `_rope`."""
    b, n, d = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]     # [b, d/2]
    cos, sin = jnp.cos(ang)[:, None, :], jnp.sin(ang)[:, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    return jnp.stack([r1, r2], axis=-1).reshape(b, n, d)


def _cache_write_row(cache_layer, new, pos):
    """cache_layer [b, n, T, hd], new [b, n, hd], pos int32 [b]."""
    return jax.vmap(
        lambda c, n_, p: jax.lax.dynamic_update_slice(
            c, n_[:, None, :].astype(c.dtype), (0, p, 0)))(
        cache_layer, new, pos.astype(jnp.int32))


def llama_prefill(params, cfg: LlamaConfig, cache, tokens, lengths):
    """Prompt pass: fill `cache` with the prompt's roped K and V and
    return (cache, logits [batch, vocab]) at each row's last real
    position.  Positions < length compute exactly what `llama_apply`
    computes."""
    dtype = jnp.dtype(cfg.dtype)
    b, t = tokens.shape
    hd = cfg.dim // cfg.heads
    rep = cfg.heads // cfg.kv_heads
    x = params["wte"][tokens].astype(dtype)
    ks, vs = [], []
    for blk in params["blocks"]:
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)

        def heads(y, n):
            return y.reshape(b, t, n, hd).transpose(0, 2, 1, 3)

        q = heads(hx @ blk["wq"].astype(dtype), cfg.heads)
        k = heads(hx @ blk["wk"].astype(dtype), cfg.kv_heads)
        v = heads(hx @ blk["wv"].astype(dtype), cfg.kv_heads)
        q = _rope(q.astype(jnp.float32), cfg.rope_theta).astype(dtype)
        k = _rope(k.astype(jnp.float32), cfg.rope_theta).astype(dtype)
        ks.append(k)
        vs.append(v)
        kf, vf = k, v
        if rep > 1:
            kf = jnp.repeat(kf, rep, axis=1)
            vf = jnp.repeat(vf, rep, axis=1)
        att = jnp.einsum("bhqd,bhkd->bhqk", q, kf) / math.sqrt(hd)
        qi = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
        att = jnp.where(ki <= qi, att, jnp.array(-1e9, att.dtype))
        att = jax.nn.softmax(att, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", att, vf)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, cfg.heads * hd)
        x = x + out @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    cache = {
        "k": cache["k"].at[:, :, :, :t, :].set(
            jnp.stack(ks).astype(cache["k"].dtype)),
        "v": cache["v"].at[:, :, :, :t, :].set(
            jnp.stack(vs).astype(cache["v"].dtype)),
    }
    x = _rmsnorm(x, params["norm_f"])
    last = jnp.take_along_axis(
        x, (lengths.astype(jnp.int32) - 1)[:, None, None], axis=1)[:, 0]
    return cache, last.astype(jnp.float32) @ params["wte"].T


def _rope_abs(x, pos, theta):
    """x: [b, n, c, d] chunk heads rotated at absolute positions `pos`
    (int32 [b, c]) — the chunked-prefill form of `_rope`/`_rope_at`."""
    b, n, c, d = x.shape
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = pos.astype(jnp.float32)[..., None] * freqs  # [b, c, d/2]
    cos = jnp.cos(ang)[:, None, :, :]
    sin = jnp.sin(ang)[:, None, :, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    return jnp.stack([r1, r2], axis=-1).reshape(b, n, c, d)


def _cache_write_chunk(cache_layer, new, start):
    """cache_layer [b, n, T, hd], new [b, n, c, hd], start int32 [b]."""
    return jax.vmap(
        lambda cl, n_, s: jax.lax.dynamic_update_slice(
            cl, n_.astype(cl.dtype), (0, s, 0)))(
        cache_layer, new, start.astype(jnp.int32))


def llama_prefill_chunk(params, cfg: LlamaConfig, cache, tokens, start_pos,
                        lengths):
    """One fixed-size prefill chunk (the llama mirror of
    `gpt.gpt_prefill_chunk`): `tokens` (int32 [batch, chunk]) at absolute
    positions `start_pos + [0..chunk)`, K/V roped at those absolute
    positions and written into `cache` at kv_heads granularity, attention
    over the FULL cache window masked to `key_pos <= query_pos`.  Returns
    (cache, logits [batch, vocab]) at each row's last real position —
    valid for rows whose chunk contains `lengths - 1`."""
    from easydist_tpu.ops import chunk_attention

    dtype = jnp.dtype(cfg.dtype)
    b, c_len = tokens.shape
    hd = cfg.dim // cfg.heads
    rep = cfg.heads // cfg.kv_heads
    start = start_pos.astype(jnp.int32)
    abs_pos = start[:, None] + jnp.arange(c_len, dtype=jnp.int32)[None, :]
    x = params["wte"][tokens].astype(dtype)
    new_k, new_v = [], []
    for li, blk in enumerate(params["blocks"]):
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)

        def heads(y, n):
            return y.reshape(b, c_len, n, hd).transpose(0, 2, 1, 3)

        q = heads(hx @ blk["wq"].astype(dtype), cfg.heads)
        k = heads(hx @ blk["wk"].astype(dtype), cfg.kv_heads)
        v = heads(hx @ blk["wv"].astype(dtype), cfg.kv_heads)
        q = _rope_abs(q.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        k = _rope_abs(k.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        ck = _cache_write_chunk(cache["k"][li], k, start)
        cv = _cache_write_chunk(cache["v"][li], v, start)
        new_k.append(ck)
        new_v.append(cv)
        kf, vf = ck.astype(dtype), cv.astype(dtype)
        if rep > 1:
            kf = jnp.repeat(kf, rep, axis=1)
            vf = jnp.repeat(vf, rep, axis=1)
        att = chunk_attention(q, kf, vf, abs_pos)
        out = att.transpose(0, 2, 1, 3).reshape(b, c_len, cfg.heads * hd)
        x = x + out @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    x = _rmsnorm(x, params["norm_f"])
    rel_last = jnp.clip(lengths.astype(jnp.int32) - 1 - start, 0, c_len - 1)
    last = jnp.take_along_axis(x, rel_last[:, None, None], axis=1)[:, 0]
    return cache, last.astype(jnp.float32) @ params["wte"].T


def llama_verify_step(params, cfg: LlamaConfig, cache, tokens, pos):
    """Speculative-decoding verify step (the llama mirror of
    `gpt.gpt_verify_step`): score `tokens` (int32 [batch, s] — last
    committed token + s-1 drafts) at absolute positions `pos + [0..s)`
    in one forward, K roped at those absolute positions and written at
    kv_heads granularity, attention over the full cache window masked to
    `key_pos <= query_pos`, GQA-repeated before attention exactly like
    the bucketed chunk path.  Returns (cache, logits [batch, s, vocab])
    for all s positions.  Callers must guarantee pos + s <= T."""
    from easydist_tpu.ops import chunk_attention

    dtype = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    hd = cfg.dim // cfg.heads
    rep = cfg.heads // cfg.kv_heads
    start = pos.astype(jnp.int32)
    abs_pos = start[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    x = params["wte"][tokens].astype(dtype)
    new_k, new_v = [], []
    for li, blk in enumerate(params["blocks"]):
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)

        def heads(y, n):
            return y.reshape(b, s, n, hd).transpose(0, 2, 1, 3)

        q = heads(hx @ blk["wq"].astype(dtype), cfg.heads)
        k = heads(hx @ blk["wk"].astype(dtype), cfg.kv_heads)
        v = heads(hx @ blk["wv"].astype(dtype), cfg.kv_heads)
        q = _rope_abs(q.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        k = _rope_abs(k.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        ck = _cache_write_chunk(cache["k"][li], k, start)
        cv = _cache_write_chunk(cache["v"][li], v, start)
        new_k.append(ck)
        new_v.append(cv)
        kf, vf = ck.astype(dtype), cv.astype(dtype)
        if rep > 1:
            kf = jnp.repeat(kf, rep, axis=1)
            vf = jnp.repeat(vf, rep, axis=1)
        att = chunk_attention(q, kf, vf, abs_pos)
        out = att.transpose(0, 2, 1, 3).reshape(b, s, cfg.heads * hd)
        x = x + out @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    x = _rmsnorm(x, params["norm_f"])
    return cache, x.astype(jnp.float32) @ params["wte"].T


def llama_decode_step(params, cfg: LlamaConfig, cache, token, pos):
    """One cached decode step: (cache, logits [batch, vocab]) for `token`
    (int32 [batch]) at absolute position `pos` (int32 [batch]).  Q and the
    new K are roped at `pos`; cached keys were roped at write time, so the
    cache is read back as-is (the relative-angle property of RoPE is paid
    at write time, once)."""
    from easydist_tpu.ops import decode_attention

    dtype = jnp.dtype(cfg.dtype)
    b = token.shape[0]
    hd = cfg.dim // cfg.heads
    rep = cfg.heads // cfg.kv_heads
    pos = pos.astype(jnp.int32)
    x = params["wte"][token].astype(dtype)
    new_k, new_v = [], []
    for li, blk in enumerate(params["blocks"]):
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)
        q = (hx @ blk["wq"].astype(dtype)).reshape(b, cfg.heads, hd)
        k = (hx @ blk["wk"].astype(dtype)).reshape(b, cfg.kv_heads, hd)
        v = (hx @ blk["wv"].astype(dtype)).reshape(b, cfg.kv_heads, hd)
        q = _rope_at(q.astype(jnp.float32), pos, cfg.rope_theta).astype(dtype)
        k = _rope_at(k.astype(jnp.float32), pos, cfg.rope_theta).astype(dtype)
        ck = _cache_write_row(cache["k"][li], k, pos)
        cv = _cache_write_row(cache["v"][li], v, pos)
        new_k.append(ck)
        new_v.append(cv)
        kf, vf = ck.astype(dtype), cv.astype(dtype)
        if rep > 1:
            kf = jnp.repeat(kf, rep, axis=1)
            vf = jnp.repeat(vf, rep, axis=1)
        att = decode_attention(q, kf, vf, pos + 1)
        x = x + att.reshape(b, cfg.heads * hd) @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    x = _rmsnorm(x, params["norm_f"])
    return cache, x.astype(jnp.float32) @ params["wte"].T


# ------------------------------------------------------- paged KV decode
#
# Page-table variants (the llama mirror of gpt.py's): the arena stores
# ROPED keys at kv_heads granularity — [layers, n_pages, kv_heads,
# page_tokens, head_dim] — so page HBM scales with kv_heads and the GQA
# repeat happens at attention time, matching the bucketed path's
# repeat-then-attend order bitwise.


def init_kv_pages(cfg: LlamaConfig, n_pages: int, page_tokens: int,
                  dtype=None, quant_dtype=None, quant_block: int = 0):
    """Zeroed page arena {"k", "v"}: [layers, n_pages, kv_heads,
    page_tokens, head_dim].  `quant_dtype="int8"` stores the payload
    block-scaled int8 plus a parallel {"k_scale", "v_scale"} f32 scale
    arena ([..., head_dim // block] — `quant_block` 0 = one block per
    row); presence of the scale keys is the quant signal the paged
    forwards branch on."""
    if n_pages < 1:
        raise ValueError(f"n_pages must be >= 1, got {n_pages}")
    if page_tokens < 1:
        raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
    hd = cfg.dim // cfg.heads
    dt = jnp.dtype(cfg.dtype if dtype in (None, "auto") else dtype)
    shape = (cfg.layers, n_pages, cfg.kv_heads, page_tokens, hd)
    if quant_dtype in (None, "none"):
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if quant_dtype != "int8":
        raise ValueError(f"quant_dtype must be None/'none'/'int8', "
                         f"got {quant_dtype!r}")
    block = quant_block or hd
    if hd % block:
        raise ValueError(f"quant_block {block} must divide head_dim {hd}")
    sshape = (cfg.layers, n_pages, cfg.kv_heads, page_tokens, hd // block)
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32)}


def _pages_write_row(pages_layer, new, write_page, offset):
    """pages_layer [n_pages, n, pt, hd], new [b, n, hd], write_page/offset
    int32 [b]; sentinel write_page entries drop (dead rows)."""
    return pages_layer.at[write_page, :, offset, :].set(
        new.astype(pages_layer.dtype), mode="drop")


def _pages_write_chunk(pages_layer, new, write_page):
    """pages_layer [n_pages, n, pt, hd], new [b, n, pt, hd], write_page
    int32 [b] — one full page per chunk; sentinel rows drop."""
    return pages_layer.at[write_page].set(
        new.astype(pages_layer.dtype), mode="drop")


def llama_prefill_chunk_paged(params, cfg: LlamaConfig, pages, table,
                              tokens, start_pos, lengths):
    """`llama_prefill_chunk` through a page table: the chunk's roped K and
    V fill the row's own page for window `start_pos // page_tokens` (no
    staging cache, no restore copy), and attention gathers the virtual
    contiguous cache through the table, GQA-repeated after the gather.
    Requires tokens.shape[1] == page_tokens."""
    from easydist_tpu.ops import (chunk_attention, gather_pages,
                                  kv_dequantize, kv_quantize)

    dtype = jnp.dtype(cfg.dtype)
    b, c_len = tokens.shape
    pt = pages["k"].shape[3]
    quant_nb = pages["k_scale"].shape[-1] if "k_scale" in pages else 0
    if c_len != pt:
        raise ValueError(f"paged prefill chunk {c_len} != page_tokens {pt} "
                         f"(chunks must fill exactly one page)")
    hd = cfg.dim // cfg.heads
    start = start_pos.astype(jnp.int32)
    tbl = table.astype(jnp.int32)
    wp = jnp.take_along_axis(tbl, (start // pt)[:, None], axis=1)[:, 0]
    abs_pos = start[:, None] + jnp.arange(c_len, dtype=jnp.int32)[None, :]
    x = params["wte"][tokens].astype(dtype)
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, blk in enumerate(params["blocks"]):
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)

        def heads(y, n):
            return y.reshape(b, c_len, n, hd).transpose(0, 2, 1, 3)

        q = heads(hx @ blk["wq"].astype(dtype), cfg.heads)
        k = heads(hx @ blk["wk"].astype(dtype), cfg.kv_heads)
        v = heads(hx @ blk["wv"].astype(dtype), cfg.kv_heads)
        q = _rope_abs(q.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        k = _rope_abs(k.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        if quant_nb:
            # ROPED keys quantize (rope at write time, like the exact
            # path stores roped keys); the GQA repeat happens after the
            # gather on BOTH payload and scales, so dequant commutes
            k, sk = kv_quantize(k, quant_nb)
            v, sv = kv_quantize(v, quant_nb)
            psk = _pages_write_chunk(pages["k_scale"][li], sk, wp)
            psv = _pages_write_chunk(pages["v_scale"][li], sv, wp)
            new_ks.append(psk)
            new_vs.append(psv)
        pk = _pages_write_chunk(pages["k"][li], k, wp)
        pv = _pages_write_chunk(pages["v"][li], v, wp)
        new_k.append(pk)
        new_v.append(pv)
        if quant_nb:
            kf = kv_dequantize(gather_pages(pk, tbl, n_heads=cfg.heads),
                               gather_pages(psk, tbl, n_heads=cfg.heads),
                               dtype)
            vf = kv_dequantize(gather_pages(pv, tbl, n_heads=cfg.heads),
                               gather_pages(psv, tbl, n_heads=cfg.heads),
                               dtype)
        else:
            kf = gather_pages(pk, tbl, n_heads=cfg.heads).astype(dtype)
            vf = gather_pages(pv, tbl, n_heads=cfg.heads).astype(dtype)
        att = chunk_attention(q, kf, vf, abs_pos)
        out = att.transpose(0, 2, 1, 3).reshape(b, c_len, cfg.heads * hd)
        x = x + out @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    pages = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quant_nb:
        pages["k_scale"] = jnp.stack(new_ks)
        pages["v_scale"] = jnp.stack(new_vs)
    x = _rmsnorm(x, params["norm_f"])
    rel_last = jnp.clip(lengths.astype(jnp.int32) - 1 - start, 0, c_len - 1)
    last = jnp.take_along_axis(x, rel_last[:, None, None], axis=1)[:, 0]
    return pages, last.astype(jnp.float32) @ params["wte"].T


def _pages_write_rows(pages_layer, new, write_page, offset):
    """pages_layer [n_pages, n, pt, hd], new [b, n, s, hd], write_page/
    offset int32 [b, s] — per-position page writes (a verify window may
    straddle a page boundary); sentinel pages drop (dead rows)."""
    return pages_layer.at[write_page, :, offset, :].set(
        new.transpose(0, 2, 1, 3).astype(pages_layer.dtype), mode="drop")


def llama_verify_step_paged(params, cfg: LlamaConfig, pages, table, tokens,
                            pos):
    """`llama_verify_step` against the page arena (the llama mirror of
    `gpt.gpt_verify_step_paged`): roped K/V rows for the s positions land
    through the table per position, attention gathers the virtual
    contiguous cache with the GQA repeat applied after the gather —
    matching the bucketed repeat-then-attend order bitwise.  Returns
    (pages, logits [batch, s, vocab]) for all s positions."""
    from easydist_tpu.ops import (chunk_attention, gather_pages,
                                  kv_dequantize, kv_quantize)

    dtype = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    pt = pages["k"].shape[3]
    quant_nb = pages["k_scale"].shape[-1] if "k_scale" in pages else 0
    hd = cfg.dim // cfg.heads
    start = pos.astype(jnp.int32)
    tbl = table.astype(jnp.int32)
    abs_pos = start[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    wp = jnp.take_along_axis(tbl, abs_pos // pt, axis=1)
    off = abs_pos % pt
    x = params["wte"][tokens].astype(dtype)
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, blk in enumerate(params["blocks"]):
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)

        def heads(y, n):
            return y.reshape(b, s, n, hd).transpose(0, 2, 1, 3)

        q = heads(hx @ blk["wq"].astype(dtype), cfg.heads)
        k = heads(hx @ blk["wk"].astype(dtype), cfg.kv_heads)
        v = heads(hx @ blk["wv"].astype(dtype), cfg.kv_heads)
        q = _rope_abs(q.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        k = _rope_abs(k.astype(jnp.float32), abs_pos,
                      cfg.rope_theta).astype(dtype)
        if quant_nb:
            k, sk = kv_quantize(k, quant_nb)
            v, sv = kv_quantize(v, quant_nb)
            psk = _pages_write_rows(pages["k_scale"][li], sk, wp, off)
            psv = _pages_write_rows(pages["v_scale"][li], sv, wp, off)
            new_ks.append(psk)
            new_vs.append(psv)
        pk = _pages_write_rows(pages["k"][li], k, wp, off)
        pv = _pages_write_rows(pages["v"][li], v, wp, off)
        new_k.append(pk)
        new_v.append(pv)
        if quant_nb:
            kf = kv_dequantize(gather_pages(pk, tbl, n_heads=cfg.heads),
                               gather_pages(psk, tbl, n_heads=cfg.heads),
                               dtype)
            vf = kv_dequantize(gather_pages(pv, tbl, n_heads=cfg.heads),
                               gather_pages(psv, tbl, n_heads=cfg.heads),
                               dtype)
        else:
            kf = gather_pages(pk, tbl, n_heads=cfg.heads).astype(dtype)
            vf = gather_pages(pv, tbl, n_heads=cfg.heads).astype(dtype)
        att = chunk_attention(q, kf, vf, abs_pos)
        out = att.transpose(0, 2, 1, 3).reshape(b, s, cfg.heads * hd)
        x = x + out @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    pages = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quant_nb:
        pages["k_scale"] = jnp.stack(new_ks)
        pages["v_scale"] = jnp.stack(new_vs)
    x = _rmsnorm(x, params["norm_f"])
    return pages, x.astype(jnp.float32) @ params["wte"].T


def llama_decode_step_paged(params, cfg: LlamaConfig, pages, table, token,
                            pos):
    """`llama_decode_step` against the page arena: the new roped K/V row
    lands at window `pos // page_tokens`, offset `pos % page_tokens`, and
    attention runs through `ops.paged_decode_attention` (the kernel maps
    query head -> kv head in its index maps; the fallback gathers then
    GQA-repeats, bitwise-matching the bucketed repeat-then-attend)."""
    from easydist_tpu.ops import kv_quantize, paged_decode_attention

    dtype = jnp.dtype(cfg.dtype)
    b = token.shape[0]
    pt = pages["k"].shape[3]
    quant_nb = pages["k_scale"].shape[-1] if "k_scale" in pages else 0
    hd = cfg.dim // cfg.heads
    pos = pos.astype(jnp.int32)
    tbl = table.astype(jnp.int32)
    wp = jnp.take_along_axis(tbl, (pos // pt)[:, None], axis=1)[:, 0]
    off = pos % pt
    x = params["wte"][token].astype(dtype)
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, blk in enumerate(params["blocks"]):
        hx = _rmsnorm(x, blk["attn_norm"]).astype(dtype)
        q = (hx @ blk["wq"].astype(dtype)).reshape(b, cfg.heads, hd)
        k = (hx @ blk["wk"].astype(dtype)).reshape(b, cfg.kv_heads, hd)
        v = (hx @ blk["wv"].astype(dtype)).reshape(b, cfg.kv_heads, hd)
        q = _rope_at(q.astype(jnp.float32), pos, cfg.rope_theta).astype(dtype)
        k = _rope_at(k.astype(jnp.float32), pos, cfg.rope_theta).astype(dtype)
        if quant_nb:
            k, sk = kv_quantize(k, quant_nb)
            v, sv = kv_quantize(v, quant_nb)
            psk = _pages_write_row(pages["k_scale"][li], sk, wp, off)
            psv = _pages_write_row(pages["v_scale"][li], sv, wp, off)
            new_ks.append(psk)
            new_vs.append(psv)
        pk = _pages_write_row(pages["k"][li], k, wp, off)
        pv = _pages_write_row(pages["v"][li], v, wp, off)
        new_k.append(pk)
        new_v.append(pv)
        if quant_nb:
            att = paged_decode_attention(q, pk, pv, tbl, pos + 1,
                                         k_scale=psk, v_scale=psv)
        else:
            att = paged_decode_attention(q, pk.astype(dtype),
                                         pv.astype(dtype), tbl, pos + 1)
        x = x + att.reshape(b, cfg.heads * hd) @ blk["wo"].astype(dtype)
        hx = _rmsnorm(x, blk["ffn_norm"]).astype(dtype)
        gated = jax.nn.silu(hx @ blk["w_gate"].astype(dtype)) \
            * (hx @ blk["w_up"].astype(dtype))
        x = x + gated @ blk["w_down"].astype(dtype)
    pages = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quant_nb:
        pages["k_scale"] = jnp.stack(new_ks)
        pages["v_scale"] = jnp.stack(new_vs)
    x = _rmsnorm(x, params["norm_f"])
    return pages, x.astype(jnp.float32) @ params["wte"].T


def llama_loss(params, cfg: LlamaConfig, tokens, targets):
    logits = llama_apply(params, cfg, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1).mean()


def make_llama_train_step(cfg: LlamaConfig, lr=1e-4):
    def init_state(key):
        params = llama_init(cfg, key)
        return (params, adam_init(params))

    def train_step(state, tokens, targets):
        params, opt = state
        loss, grads = jax.value_and_grad(llama_loss)(params, cfg, tokens,
                                                     targets)
        new_params, new_opt = adam_update(params, grads, opt, lr=lr)
        return (new_params, new_opt), loss

    return train_step, init_state
