"""Graph attention network (reference benchmark: GAT 4096 nodes x 12288
features, benchmark/bench_case.py:21-25; model benchmark/torch/model/gat.py
behavior).  Dense-adjacency formulation — static shapes for XLA."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp

from .optim import sgd_update


@dataclass
class GATConfig:
    nodes: int = 4096
    features: int = 12288
    hidden: int = 256
    classes: int = 16
    layers: int = 2

    @staticmethod
    def bench(**kw):
        return GATConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(nodes=64, features=32, hidden=16, classes=4, layers=2)
        base.update(kw)
        return GATConfig(**base)


def gat_init(cfg: GATConfig, key) -> Dict:
    dims = [cfg.features] + [cfg.hidden] * (cfg.layers - 1) + [cfg.classes]
    params = {"layers": []}
    keys = jax.random.split(key, cfg.layers)
    for k, d_in, d_out in zip(keys, dims[:-1], dims[1:]):
        k1, k2, k3 = jax.random.split(k, 3)
        params["layers"].append({
            "w": jax.random.normal(k1, (d_in, d_out)) / math.sqrt(d_in),
            "a_src": jax.random.normal(k2, (d_out,)) / math.sqrt(d_out),
            "a_dst": jax.random.normal(k3, (d_out,)) / math.sqrt(d_out),
        })
    return params


def gat_apply(params, adj, x):
    """adj: [N, N] dense 0/1 adjacency (self-loops included); x: [N, F]."""
    h = x
    for i, layer in enumerate(params["layers"]):
        z = h @ layer["w"]  # [N, D]
        e_src = z @ layer["a_src"]  # [N]
        e_dst = z @ layer["a_dst"]  # [N]
        e = jax.nn.leaky_relu(e_src[:, None] + e_dst[None, :], 0.2)
        e = jnp.where(adj > 0, e, -1e30)
        att = jax.nn.softmax(e, axis=-1)
        h = att @ z
        if i < len(params["layers"]) - 1:
            h = jax.nn.elu(h)
    return h


def make_gat_train_step(cfg: GATConfig, lr=1e-2):
    def train_step(params, adj, x, labels):
        def loss_fn(p):
            logits = gat_apply(p, adj, x)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return sgd_update(params, grads, lr=lr), loss

    return train_step
