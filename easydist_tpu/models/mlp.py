"""Plain MLP — the minimal end-to-end model (reference examples use an
equivalent toy net for the jax example: examples/jax/simple_function.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .optim import sgd_update


def mlp_init(key, sizes=(16, 64, 64, 8)):
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, n_in, n_out in zip(keys, sizes[:-1], sizes[1:]):
        params.append({"w": jax.random.normal(k, (n_in, n_out)) / jnp.sqrt(n_in),
                       "b": jnp.zeros((n_out,))})
    return params


def mlp_apply(params, x):
    for layer in params[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ params[-1]["w"] + params[-1]["b"]


def make_mlp_train_step(lr=1e-2):
    def train_step(params, x, y):
        def loss_fn(p):
            return jnp.mean((mlp_apply(p, x) - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return sgd_update(params, grads, lr=lr), loss

    return train_step
