"""GPT-2-style decoder transformer, pure jax (reference headline model:
benchmark/torch/model/gpt.py; config GPT bs4 seq1024 d12288 h48 in
benchmark/bench_case.py:5-14).

TPU-first choices: bf16-ready matmuls on the MXU, static causal mask via
lax.select on an iota comparison (no data-dependent control flow), shapes
kept multiples of 128 at real sizes."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp

from .optim import adam_init, adam_update


@dataclass
class GPTConfig:
    vocab: int = 50257
    seq: int = 1024
    dim: int = 768
    heads: int = 12
    layers: int = 12
    dtype: str = "float32"  # compute dtype; params stay float32
    # attention backend: "einsum" (XLA), "flash" (Pallas kernel), "ring"
    # (sequence-parallel ring attention; needs attn_mesh + attn_axis), or
    # "auto" (solver-visible composite — the auto-parallel ILP chooses
    # batch/head/seq-ring/seq-Ulysses per mesh axis)
    attention: str = "einsum"
    attn_mesh: object = None
    attn_axis: str = "sp"
    # per-block rematerialization: "none", "full" (jax.checkpoint each
    # block), or "dots" (save matmul outputs only) — trades recompute for
    # O(layers) instead of O(layers x activations) live memory in the bwd
    remat: str = "none"
    # rolled layers: params["blocks"] is a layer-stacked pytree (leading dim
    # = layers) and the forward runs one lax.scan over it — XLA compiles the
    # block once regardless of depth (the idiomatic Llama-scale form; the
    # auto-parallel path shards through the scan via the composite rule in
    # jaxfront/interpreter.py::_discover_scan)
    scan_layers: bool = False

    @staticmethod
    def small(**kw):
        return GPTConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab=128, seq=32, dim=32, heads=4, layers=2)
        base.update(kw)
        return GPTConfig(**base)


def _init_linear(key, n_in, n_out, scale=None):
    scale = scale if scale is not None else 1.0 / math.sqrt(n_in)
    wk, _ = jax.random.split(key)
    return {"w": jax.random.normal(wk, (n_in, n_out)) * scale,
            "b": jnp.zeros((n_out,))}


def gpt_init(cfg: GPTConfig, key) -> Dict:
    keys = jax.random.split(key, 2 + cfg.layers)
    params = {
        "wte": jax.random.normal(keys[0], (cfg.vocab, cfg.dim)) * 0.02,
        "wpe": jax.random.normal(keys[1], (cfg.seq, cfg.dim)) * 0.01,
        "blocks": [],
        "ln_f": {"g": jnp.ones((cfg.dim,)), "b": jnp.zeros((cfg.dim,))},
    }
    proj_scale = 1.0 / math.sqrt(cfg.dim) / math.sqrt(2.0 * cfg.layers)
    for i in range(cfg.layers):
        bk = jax.random.split(keys[2 + i], 4)
        params["blocks"].append({
            "ln1": {"g": jnp.ones((cfg.dim,)), "b": jnp.zeros((cfg.dim,))},
            "attn": {
                "qkv": _init_linear(bk[0], cfg.dim, 3 * cfg.dim),
                "proj": _init_linear(bk[1], cfg.dim, cfg.dim, proj_scale),
            },
            "ln2": {"g": jnp.ones((cfg.dim,)), "b": jnp.zeros((cfg.dim,))},
            "mlp": {
                "fc": _init_linear(bk[2], cfg.dim, 4 * cfg.dim),
                "proj": _init_linear(bk[3], 4 * cfg.dim, cfg.dim, proj_scale),
            },
        })
    if cfg.scan_layers:
        params["blocks"] = stack_gpt_blocks(params["blocks"])
    return params


def stack_gpt_blocks(blocks):
    """Per-layer block list -> one layer-stacked pytree (leading dim L)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def _layernorm(x, g, b, eps=1e-5):
    mu = x.mean(axis=-1, keepdims=True)
    var = x.var(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _attention(x, p, cfg: "GPTConfig", dtype, return_kv: bool = False):
    heads = cfg.heads
    b, t, d = x.shape
    hd = d // heads
    qkv = x @ p["qkv"]["w"].astype(dtype) + p["qkv"]["b"].astype(dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def split_heads(t_):
        return t_.reshape(b, t, heads, hd).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if cfg.attention == "auto":
        # solver-visible composite: the auto-parallel ILP picks batch/head/
        # sequence (ring or Ulysses) sharding per mesh axis and emission
        # lowers accordingly (ops/attention_prim.py)
        from easydist_tpu.ops.attention_prim import attention as ed_attention

        out = ed_attention(q, k, v, causal=True)
    elif cfg.attention == "flash":
        from easydist_tpu.ops import flash_attention

        out = flash_attention(q, k, v, True)
    elif cfg.attention == "ring":
        from easydist_tpu.parallel import ring_attention

        out = ring_attention(q, k, v, cfg.attn_mesh, axis=cfg.attn_axis,
                             causal=True)
    else:
        att = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
        qi = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
        att = jnp.where(ki <= qi, att, jnp.array(-1e9, dtype=att.dtype))
        att = jax.nn.softmax(att, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    out = out @ p["proj"]["w"].astype(dtype) + p["proj"]["b"].astype(dtype)
    if return_kv:
        return out, k, v  # k, v: [b, heads, t, hd], pre-projection
    return out


def gpt_apply(params, cfg: GPTConfig, tokens):
    """tokens: int32 [batch, seq] -> logits [batch, seq, vocab]."""
    dtype = jnp.dtype(cfg.dtype)
    x = params["wte"][tokens].astype(dtype) + params["wpe"].astype(dtype)[None, :tokens.shape[1]]
    def block_fn(blk, x):
        x = x + _attention(
            _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype),
            blk["attn"], cfg, dtype)
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        return x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                    + blk["mlp"]["proj"]["b"].astype(dtype))

    # per-block remat is driven ONLY by cfg.remat; the EASYDIST_REMAT_POLICY
    # env knob applies to compiled-function emission (jaxfront/api.py), a
    # separate mechanism — stacking both from one knob would double-remat
    remat = cfg.remat
    if remat not in ("none", "full", "dots"):
        raise ValueError(f"unknown GPTConfig.remat {cfg.remat!r}; "
                         f"expected none|full|dots")
    if remat == "full":
        block_fn = jax.checkpoint(block_fn)
    elif remat == "dots":
        block_fn = jax.checkpoint(
            block_fn, policy=jax.checkpoint_policies.checkpoint_dots)
    if cfg.scan_layers:
        x, _ = jax.lax.scan(lambda h, blk: (block_fn(blk, h), None),
                            x, params["blocks"])
    else:
        for blk in params["blocks"]:
            x = block_fn(blk, x)
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return x.astype(jnp.float32) @ params["wte"].T


# --------------------------------------------------------- KV-cache decode
#
# Autoregressive serving forward: `gpt_prefill` runs the prompt once and
# fills a per-layer K/V cache; `gpt_decode_step` then attends ONE new token
# against the cache — O(layers * len) per token instead of the O(len^2)
# full re-forward.  Both are pure functions returning the updated cache, so
# a jit of the step with the cache input donated updates it in place
# (analyze rule SERVE001 audits exactly that).


def init_kv_cache(cfg: GPTConfig, batch: int, max_len: int, dtype=None):
    """Zeroed KV cache {"k", "v"}: [layers, batch, heads, max_len,
    head_dim].  Layer-stacked so the cache is two leaves regardless of
    depth (donation and sharding specs stay O(1)); the heads axis (dim 2)
    is the natural tensor-parallel shard dim, matching the solved qkv
    column-parallel strategy.  `dtype=None`/"auto" stores at the compute
    dtype; pass e.g. "bfloat16" to halve cache HBM."""
    if max_len > cfg.seq:
        raise ValueError(
            f"max_len {max_len} exceeds the learned position table "
            f"(cfg.seq={cfg.seq})")
    hd = cfg.dim // cfg.heads
    dt = jnp.dtype(cfg.dtype if dtype in (None, "auto") else dtype)
    shape = (cfg.layers, batch, cfg.heads, max_len, hd)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _block_list(params, cfg):
    """Per-layer block pytrees whether `params["blocks"]` is a list or the
    scan_layers layer-stacked form."""
    blocks = params["blocks"]
    if cfg.scan_layers:
        return [jax.tree_util.tree_map(lambda p, i=i: p[i], blocks)
                for i in range(cfg.layers)]
    return list(blocks)


def _cache_write_row(cache_layer, new, pos):
    """Write one new K or V row per sequence: cache_layer [b, h, T, hd],
    new [b, h, hd], pos int32 [b] -> updated layer.  Per-row
    dynamic_update_slice touches only each sequence's own position."""
    return jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(
            c, n[:, None, :].astype(c.dtype), (0, p, 0)))(
        cache_layer, new, pos.astype(jnp.int32))


def gpt_prefill(params, cfg: GPTConfig, cache, tokens, lengths):
    """Prompt pass: run `tokens` (int32 [batch, t], padded) through the
    model, write every position's K/V into `cache`, and return
    (cache, logits) with logits [batch, vocab] taken at each row's last
    real position (`lengths` - 1).

    The attention is the standard causal forward, so positions < length
    compute exactly what `gpt_apply` computes; the padded tail writes
    garbage K/V that the decode-step length mask never attends."""
    dtype = jnp.dtype(cfg.dtype)
    b, t = tokens.shape
    x = params["wte"][tokens].astype(dtype) \
        + params["wpe"].astype(dtype)[None, :t]
    ks, vs = [], []
    for blk in _block_list(params, cfg):
        attn_out, k, v = _attention(
            _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype),
            blk["attn"], cfg, dtype, return_kv=True)
        x = x + attn_out
        ks.append(k)
        vs.append(v)
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    cache = {
        "k": cache["k"].at[:, :, :, :t, :].set(
            jnp.stack(ks).astype(cache["k"].dtype)),
        "v": cache["v"].at[:, :, :, :t, :].set(
            jnp.stack(vs).astype(cache["v"].dtype)),
    }
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    last = jnp.take_along_axis(
        x, (lengths.astype(jnp.int32) - 1)[:, None, None], axis=1)[:, 0]
    return cache, last.astype(jnp.float32) @ params["wte"].T


def _cache_write_chunk(cache_layer, new, start):
    """Write a fixed-size chunk of K or V rows per sequence: cache_layer
    [b, h, T, hd], new [b, h, c, hd], start int32 [b] -> updated layer.
    Per-row dynamic_update_slice at a traced start keeps ONE compiled
    signature across every chunk position."""
    return jax.vmap(
        lambda cl, n, s: jax.lax.dynamic_update_slice(
            cl, n.astype(cl.dtype), (0, s, 0)))(
        cache_layer, new, start.astype(jnp.int32))


def gpt_prefill_chunk(params, cfg: GPTConfig, cache, tokens, start_pos,
                      lengths):
    """One fixed-size prefill chunk: run `tokens` (int32 [batch, chunk])
    at absolute positions `start_pos + [0..chunk)` (int32 [batch]), write
    the chunk's K/V into `cache` at those positions, and return
    (cache, logits [batch, vocab]) taken at each row's last real position
    — valid for rows whose chunk contains `lengths - 1` (the finishing
    chunk), garbage otherwise (the scheduler only reads finishing rows).

    Unlike `gpt_prefill` this attends the FULL cache window [0, T) with a
    `key_pos <= query_pos` mask, so the traced shape is independent of how
    much prompt is already cached: one compiled signature per bucket
    replaces the per-pow2-length set, and restored prefix chunks (written
    by a previous request via the prefix trie) are consumed exactly as if
    recomputed — softmax weights past a row's live positions underflow to
    exact 0, the stale-row-leakage property analyze SERVE002 audits."""
    from easydist_tpu.ops import chunk_attention

    dtype = jnp.dtype(cfg.dtype)
    heads = cfg.heads
    b, c_len = tokens.shape
    hd = cfg.dim // heads
    start = start_pos.astype(jnp.int32)
    # absolute positions of this chunk's queries, per row: [b, chunk]
    abs_pos = start[:, None] + jnp.arange(c_len, dtype=jnp.int32)[None, :]
    x = params["wte"][tokens].astype(dtype) \
        + params["wpe"][abs_pos].astype(dtype)
    new_k, new_v = [], []
    for li, blk in enumerate(_block_list(params, cfg)):
        p_at = blk["attn"]
        h_in = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype)
        qkv = h_in @ p_at["qkv"]["w"].astype(dtype) \
            + p_at["qkv"]["b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, c_len, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, c_len, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, c_len, heads, hd).transpose(0, 2, 1, 3)
        ck = _cache_write_chunk(cache["k"][li], k, start)
        cv = _cache_write_chunk(cache["v"][li], v, start)
        new_k.append(ck)
        new_v.append(cv)
        att = chunk_attention(q, ck.astype(dtype), cv.astype(dtype),
                              abs_pos)
        att = att.transpose(0, 2, 1, 3).reshape(b, c_len, cfg.dim)
        x = x + (att @ p_at["proj"]["w"].astype(dtype)
                 + p_at["proj"]["b"].astype(dtype))
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    rel_last = jnp.clip(lengths.astype(jnp.int32) - 1 - start, 0, c_len - 1)
    last = jnp.take_along_axis(x, rel_last[:, None, None], axis=1)[:, 0]
    return cache, last.astype(jnp.float32) @ params["wte"].T


def gpt_verify_step(params, cfg: GPTConfig, cache, tokens, pos):
    """Speculative-decoding verify step: score `tokens` (int32
    [batch, s] — each row is [last committed token, draft_0, ...,
    draft_{s-2}]) at absolute positions `pos + [0..s)` in ONE forward,
    returning (cache, logits [batch, s, vocab]) for ALL s positions, so
    the host can accept the longest greedily-matching draft prefix.

    The trunk is `gpt_prefill_chunk` with s as the chunk length: K/V for
    all s positions is written at the traced start `pos` (one compiled
    signature per (bucket, s)) and attention over the full cache window
    is masked to `key_pos <= query_pos`, so position i's logits equal
    what `gpt_decode_step` would produce after sequentially feeding the
    first i tokens — rejected-draft rows written past the accept
    boundary are exactly the stale rows the mask keeps out of every
    later step (analyze rule SERVE003 audits this mask).  Callers must
    guarantee pos + s <= T (the write would otherwise be clamped onto
    committed rows)."""
    from easydist_tpu.ops import chunk_attention

    dtype = jnp.dtype(cfg.dtype)
    heads = cfg.heads
    b, s = tokens.shape
    hd = cfg.dim // heads
    start = pos.astype(jnp.int32)
    abs_pos = start[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    x = params["wte"][tokens].astype(dtype) \
        + params["wpe"][abs_pos].astype(dtype)
    new_k, new_v = [], []
    for li, blk in enumerate(_block_list(params, cfg)):
        p_at = blk["attn"]
        h_in = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype)
        qkv = h_in @ p_at["qkv"]["w"].astype(dtype) \
            + p_at["qkv"]["b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
        ck = _cache_write_chunk(cache["k"][li], k, start)
        cv = _cache_write_chunk(cache["v"][li], v, start)
        new_k.append(ck)
        new_v.append(cv)
        att = chunk_attention(q, ck.astype(dtype), cv.astype(dtype),
                              abs_pos)
        att = att.transpose(0, 2, 1, 3).reshape(b, s, cfg.dim)
        x = x + (att @ p_at["proj"]["w"].astype(dtype)
                 + p_at["proj"]["b"].astype(dtype))
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return cache, x.astype(jnp.float32) @ params["wte"].T


def gpt_decode_step(params, cfg: GPTConfig, cache, token, pos):
    """One cached decode step: feed `token` (int32 [batch]) at position
    `pos` (int32 [batch], == current sequence length per row) and return
    (cache, logits [batch, vocab]) for sampling the next token.

    Per-token work is O(layers * pos) attention reads plus the O(1)
    matmuls — independent of how many tokens were already generated.  The
    attention backend is `ops.decode_attention` (Pallas single-query flash
    kernel on TPU, masked dot_general elsewhere)."""
    from easydist_tpu.ops import decode_attention

    dtype = jnp.dtype(cfg.dtype)
    heads = cfg.heads
    b = token.shape[0]
    hd = cfg.dim // heads
    pos = pos.astype(jnp.int32)
    x = params["wte"][token].astype(dtype) \
        + params["wpe"][pos].astype(dtype)
    new_k, new_v = [], []
    for li, blk in enumerate(_block_list(params, cfg)):
        p_at = blk["attn"]
        h_in = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype)
        qkv = h_in @ p_at["qkv"]["w"].astype(dtype) \
            + p_at["qkv"]["b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, heads, hd)
        ck = _cache_write_row(cache["k"][li], k.reshape(b, heads, hd), pos)
        cv = _cache_write_row(cache["v"][li], v.reshape(b, heads, hd), pos)
        new_k.append(ck)
        new_v.append(cv)
        att = decode_attention(q, ck.astype(dtype), cv.astype(dtype),
                               pos + 1)
        x = x + (att.reshape(b, cfg.dim) @ p_at["proj"]["w"].astype(dtype)
                 + p_at["proj"]["b"].astype(dtype))
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return cache, x.astype(jnp.float32) @ params["wte"].T


# ------------------------------------------------------- paged KV decode
#
# Page-table variants of the serving forwards: K/V lives in one
# preallocated page arena ({"k","v"}: [layers, n_pages, heads,
# page_tokens, head_dim]) and each sequence's int32 page-table row says
# which arena page holds each `page_tokens`-token window.  The arena is
# threaded through (and donated) exactly like the contiguous cache; the
# table is a few KiB of int32 pushed fresh each step.  Unmapped/dead
# entries hold the sentinel `n_pages`: writes through it scatter with
# mode="drop" (deterministically discarded), reads clip to a real page
# whose rows the length mask zeroes before softmax.


def init_kv_pages(cfg: GPTConfig, n_pages: int, page_tokens: int,
                  dtype=None, quant_dtype=None, quant_block: int = 0):
    """Zeroed page arena {"k", "v"}: [layers, n_pages, heads, page_tokens,
    head_dim].  Pages replace the batch axis of `init_kv_cache` at the
    same dim index, so `kv_cache_specs` shards heads (dim 2) on "tp"
    identically for both layouts.

    `quant_dtype="int8"` stores the payload block-scaled int8 and adds a
    parallel scale arena {"k_scale", "v_scale"}: [layers, n_pages, heads,
    page_tokens, head_dim // block] f32 (`quant_block` 0 = one block per
    row).  Presence of the scale keys is the quant signal every paged
    forward branches on — a {"k","v"}-only arena traces the exact
    pre-quant program."""
    if n_pages < 1:
        raise ValueError(f"n_pages must be >= 1, got {n_pages}")
    if page_tokens < 1:
        raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
    hd = cfg.dim // cfg.heads
    dt = jnp.dtype(cfg.dtype if dtype in (None, "auto") else dtype)
    shape = (cfg.layers, n_pages, cfg.heads, page_tokens, hd)
    if quant_dtype in (None, "none"):
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if quant_dtype != "int8":
        raise ValueError(f"quant_dtype must be None/'none'/'int8', "
                         f"got {quant_dtype!r}")
    block = quant_block or hd
    if hd % block:
        raise ValueError(f"quant_block {block} must divide head_dim {hd}")
    sshape = (cfg.layers, n_pages, cfg.heads, page_tokens, hd // block)
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32)}


def _pages_write_row(pages_layer, new, write_page, offset):
    """Write one new K or V row per sequence through the page table:
    pages_layer [n_pages, h, pt, hd], new [b, h, hd], write_page int32 [b]
    (the arena page holding each row's current window; sentinel n_pages
    for dead rows), offset int32 [b] (position within the page).  The two
    advanced indices put the batch dim in front of the update, and
    mode="drop" discards sentinel writes — dead rows touch nothing."""
    return pages_layer.at[write_page, :, offset, :].set(
        new.astype(pages_layer.dtype), mode="drop")


def _pages_write_chunk(pages_layer, new, write_page):
    """Write one full page-sized chunk of K or V per sequence:
    pages_layer [n_pages, h, pt, hd], new [b, h, pt, hd], write_page
    int32 [b].  Chunked prefill is page-aligned by construction
    (page_tokens == prefill chunk), so a chunk always fills exactly one
    freshly-allocated page; sentinel rows drop."""
    return pages_layer.at[write_page].set(
        new.astype(pages_layer.dtype), mode="drop")


def gpt_prefill_chunk_paged(params, cfg: GPTConfig, pages, table, tokens,
                            start_pos, lengths):
    """`gpt_prefill_chunk` with the cache indirected through a page table:
    `pages` is the arena, `table` int32 [batch, max_pages] maps each row's
    windows to arena pages (sentinel-padded), and the chunk's K/V is
    written INTO the row's own page for window `start_pos // page_tokens`
    — there is no staging cache and no migrate/restore copy on the paged
    path; a restored prefix is just table entries pointing at the trie's
    committed pages.  Attention gathers the virtual contiguous cache
    [batch, heads, max_pages * page_tokens, head_dim] through the table,
    so when that length equals the bucketed window the lowered program
    matches `gpt_prefill_chunk` shape-for-shape and the logits are
    bitwise identical.  Requires tokens.shape[1] == page_tokens."""
    from easydist_tpu.ops import (chunk_attention, gather_pages,
                                  kv_dequantize, kv_quantize)

    dtype = jnp.dtype(cfg.dtype)
    heads = cfg.heads
    b, c_len = tokens.shape
    pt = pages["k"].shape[3]
    quant_nb = pages["k_scale"].shape[-1] if "k_scale" in pages else 0
    if c_len != pt:
        raise ValueError(f"paged prefill chunk {c_len} != page_tokens {pt} "
                         f"(chunks must fill exactly one page)")
    hd = cfg.dim // heads
    start = start_pos.astype(jnp.int32)
    tbl = table.astype(jnp.int32)
    # the page receiving this chunk: the row's window start // page_tokens
    # (sentinel for inactive rows -> the writes drop)
    wp = jnp.take_along_axis(tbl, (start // pt)[:, None], axis=1)[:, 0]
    abs_pos = start[:, None] + jnp.arange(c_len, dtype=jnp.int32)[None, :]
    x = params["wte"][tokens].astype(dtype) \
        + params["wpe"][abs_pos].astype(dtype)
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, blk in enumerate(_block_list(params, cfg)):
        p_at = blk["attn"]
        h_in = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype)
        qkv = h_in @ p_at["qkv"]["w"].astype(dtype) \
            + p_at["qkv"]["b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, c_len, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, c_len, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, c_len, heads, hd).transpose(0, 2, 1, 3)
        if quant_nb:
            # quantize-on-commit: the page stores block-scaled int8, the
            # scale page rides the same write/gather indices
            k, sk = kv_quantize(k, quant_nb)
            v, sv = kv_quantize(v, quant_nb)
            psk = _pages_write_chunk(pages["k_scale"][li], sk, wp)
            psv = _pages_write_chunk(pages["v_scale"][li], sv, wp)
            new_ks.append(psk)
            new_vs.append(psv)
        pk = _pages_write_chunk(pages["k"][li], k, wp)
        pv = _pages_write_chunk(pages["v"][li], v, wp)
        new_k.append(pk)
        new_v.append(pv)
        # gather AFTER the write so the chunk attends its own fresh page
        if quant_nb:
            ck = kv_dequantize(gather_pages(pk, tbl),
                               gather_pages(psk, tbl), dtype)
            cv = kv_dequantize(gather_pages(pv, tbl),
                               gather_pages(psv, tbl), dtype)
        else:
            ck = gather_pages(pk, tbl)
            cv = gather_pages(pv, tbl)
        att = chunk_attention(q, ck.astype(dtype), cv.astype(dtype),
                              abs_pos)
        att = att.transpose(0, 2, 1, 3).reshape(b, c_len, cfg.dim)
        x = x + (att @ p_at["proj"]["w"].astype(dtype)
                 + p_at["proj"]["b"].astype(dtype))
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    pages = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quant_nb:
        pages["k_scale"] = jnp.stack(new_ks)
        pages["v_scale"] = jnp.stack(new_vs)
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    rel_last = jnp.clip(lengths.astype(jnp.int32) - 1 - start, 0, c_len - 1)
    last = jnp.take_along_axis(x, rel_last[:, None, None], axis=1)[:, 0]
    return pages, last.astype(jnp.float32) @ params["wte"].T


def _pages_write_rows(pages_layer, new, write_page, offset):
    """Write `s` consecutive K or V rows per sequence through the page
    table: pages_layer [n_pages, h, pt, hd], new [b, h, s, hd],
    write_page/offset int32 [b, s] (per position — a run of s positions
    may straddle a page boundary, so each resolves its own page).  The
    advanced indices broadcast to [b, s] in front of the update, and
    mode="drop" discards sentinel pages — dead rows touch nothing."""
    return pages_layer.at[write_page, :, offset, :].set(
        new.transpose(0, 2, 1, 3).astype(pages_layer.dtype), mode="drop")


def gpt_verify_step_paged(params, cfg: GPTConfig, pages, table, tokens,
                          pos):
    """`gpt_verify_step` against the page arena: the s positions'
    K/V rows land through the table per position (windows
    `(pos + i) // page_tokens`, offsets `(pos + i) % page_tokens` — a
    verify window may straddle a page boundary, unlike page-aligned
    prefill chunks), and attention gathers the virtual contiguous cache
    through the table as the paged prefill chunk does.  Returns
    (pages, logits [batch, s, vocab]) for all s positions.  Callers must
    have every touched window mapped (or the whole row sentinel — dead
    rows drop); rejected positions live in mapped pages until the host
    truncates the table tail past the reservation."""
    from easydist_tpu.ops import (chunk_attention, gather_pages,
                                  kv_dequantize, kv_quantize)

    dtype = jnp.dtype(cfg.dtype)
    heads = cfg.heads
    b, s = tokens.shape
    pt = pages["k"].shape[3]
    quant_nb = pages["k_scale"].shape[-1] if "k_scale" in pages else 0
    hd = cfg.dim // heads
    start = pos.astype(jnp.int32)
    tbl = table.astype(jnp.int32)
    abs_pos = start[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    # per-position page + offset: [b, s] each (sentinel rows stay
    # sentinel through the take -> every write drops)
    wp = jnp.take_along_axis(tbl, abs_pos // pt, axis=1)
    off = abs_pos % pt
    x = params["wte"][tokens].astype(dtype) \
        + params["wpe"][abs_pos].astype(dtype)
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, blk in enumerate(_block_list(params, cfg)):
        p_at = blk["attn"]
        h_in = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype)
        qkv = h_in @ p_at["qkv"]["w"].astype(dtype) \
            + p_at["qkv"]["b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, heads, hd).transpose(0, 2, 1, 3)
        if quant_nb:
            k, sk = kv_quantize(k, quant_nb)
            v, sv = kv_quantize(v, quant_nb)
            psk = _pages_write_rows(pages["k_scale"][li], sk, wp, off)
            psv = _pages_write_rows(pages["v_scale"][li], sv, wp, off)
            new_ks.append(psk)
            new_vs.append(psv)
        pk = _pages_write_rows(pages["k"][li], k, wp, off)
        pv = _pages_write_rows(pages["v"][li], v, wp, off)
        new_k.append(pk)
        new_v.append(pv)
        if quant_nb:
            ck = kv_dequantize(gather_pages(pk, tbl),
                               gather_pages(psk, tbl), dtype)
            cv = kv_dequantize(gather_pages(pv, tbl),
                               gather_pages(psv, tbl), dtype)
        else:
            ck = gather_pages(pk, tbl)
            cv = gather_pages(pv, tbl)
        att = chunk_attention(q, ck.astype(dtype), cv.astype(dtype),
                              abs_pos)
        att = att.transpose(0, 2, 1, 3).reshape(b, s, cfg.dim)
        x = x + (att @ p_at["proj"]["w"].astype(dtype)
                 + p_at["proj"]["b"].astype(dtype))
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    pages = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quant_nb:
        pages["k_scale"] = jnp.stack(new_ks)
        pages["v_scale"] = jnp.stack(new_vs)
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return pages, x.astype(jnp.float32) @ params["wte"].T


def gpt_decode_step_paged(params, cfg: GPTConfig, pages, table, token, pos):
    """`gpt_decode_step` against the page arena: the new token's K/V row
    lands in the page holding window `pos // page_tokens` at offset
    `pos % page_tokens`, and attention runs through
    `ops.paged_decode_attention` (page-gathering Pallas kernel on TPU,
    gather + masked dot_general elsewhere).  The table's fixed
    [batch, max_pages] shape keeps ONE compiled signature across
    arbitrary per-row lengths — the whole point of the paged pool."""
    from easydist_tpu.ops import kv_quantize, paged_decode_attention

    dtype = jnp.dtype(cfg.dtype)
    heads = cfg.heads
    b = token.shape[0]
    pt = pages["k"].shape[3]
    quant_nb = pages["k_scale"].shape[-1] if "k_scale" in pages else 0
    hd = cfg.dim // heads
    pos = pos.astype(jnp.int32)
    tbl = table.astype(jnp.int32)
    wp = jnp.take_along_axis(tbl, (pos // pt)[:, None], axis=1)[:, 0]
    off = pos % pt
    x = params["wte"][token].astype(dtype) \
        + params["wpe"][pos].astype(dtype)
    new_k, new_v = [], []
    new_ks, new_vs = [], []
    for li, blk in enumerate(_block_list(params, cfg)):
        p_at = blk["attn"]
        h_in = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype)
        qkv = h_in @ p_at["qkv"]["w"].astype(dtype) \
            + p_at["qkv"]["b"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, heads, hd)
        k = k.reshape(b, heads, hd)
        v = v.reshape(b, heads, hd)
        if quant_nb:
            k, sk = kv_quantize(k, quant_nb)
            v, sv = kv_quantize(v, quant_nb)
            psk = _pages_write_row(pages["k_scale"][li], sk, wp, off)
            psv = _pages_write_row(pages["v_scale"][li], sv, wp, off)
            new_ks.append(psk)
            new_vs.append(psv)
        pk = _pages_write_row(pages["k"][li], k, wp, off)
        pv = _pages_write_row(pages["v"][li], v, wp, off)
        new_k.append(pk)
        new_v.append(pv)
        if quant_nb:
            # int8 pages stream to the kernel as-is; dequantization
            # happens inside the online-softmax loop (or post-gather in
            # the XLA fallback)
            att = paged_decode_attention(q, pk, pv, tbl, pos + 1,
                                         k_scale=psk, v_scale=psv)
        else:
            att = paged_decode_attention(q, pk.astype(dtype),
                                         pv.astype(dtype), tbl, pos + 1)
        x = x + (att.reshape(b, cfg.dim) @ p_at["proj"]["w"].astype(dtype)
                 + p_at["proj"]["b"].astype(dtype))
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
        h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                        + blk["mlp"]["fc"]["b"].astype(dtype))
        x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                 + blk["mlp"]["proj"]["b"].astype(dtype))
    pages = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quant_nb:
        pages["k_scale"] = jnp.stack(new_ks)
        pages["v_scale"] = jnp.stack(new_vs)
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return pages, x.astype(jnp.float32) @ params["wte"].T


def gpt_loss(params, cfg: GPTConfig, tokens, targets):
    logits = gpt_apply(params, cfg, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return nll.mean()


def make_gpt_train_step(cfg: GPTConfig, lr=1e-4):
    """Returns (train_step, init_state): state = (params, opt_state);
    step(state, tokens, targets) -> (new_state, loss)."""

    def init_state(key):
        params = gpt_init(cfg, key)
        return (params, adam_init(params))

    def train_step(state, tokens, targets):
        params, opt = state
        loss, grads = jax.value_and_grad(gpt_loss)(params, cfg, tokens, targets)
        new_params, new_opt = adam_update(params, grads, opt, lr=lr)
        return (new_params, new_opt), loss

    return train_step, init_state


def make_gpt_pipeline_step(cfg: GPTConfig, mesh, n_microbatches: int,
                           lr: float = 1e-4, axis: str = "pp",
                           data_axis=None, schedule: str = "gpipe",
                           n_virtual: int = 1):
    """Pipeline-parallel GPT training: transformer blocks pipelined over the
    `pp` mesh axis (stage-stacked params), embedding/positional/head outside
    the pipelined middle (reference scenario: benchmark/torch/pp/gpt).

    schedule="gpipe"/"remat" differentiates through the forward pipeline;
    schedule="1f1b" runs the DAPPLE-class supertick schedule with
    O(n_stages) live microbatches, backpropagating into the embedding and
    head via the pipeline's aux input/head gradients.  n_virtual>1
    interleaves virtual stage chunks under ANY schedule.

    Requires cfg.layers % (n_stages * n_virtual) == 0.  Returns
    (train_step, init_state): state = (params, opt); train_step(state,
    tokens, targets) -> (state, loss); tokens [n_microbatches, mb, seq].
    """
    from easydist_tpu.parallel import (PipelineConfig, spmd_pipeline,
                                       spmd_pipeline_grad)

    n_stages = mesh.shape[axis]
    n_chunks = n_stages * max(1, n_virtual)
    if cfg.layers % n_chunks != 0:
        raise ValueError(f"layers {cfg.layers} not divisible by "
                         f"{n_chunks} pipeline stages x virtual chunks")
    per_stage = cfg.layers // n_chunks
    dtype = jnp.dtype(cfg.dtype)

    def stage_fn(stage_blocks, x):
        # stage_blocks: block pytree with leading dim per_stage
        for i in range(per_stage):
            blk = jax.tree_util.tree_map(lambda p: p[i], stage_blocks)
            x = x + _attention(
                _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]).astype(dtype),
                blk["attn"], cfg, dtype)
            h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"]).astype(dtype)
            h = jax.nn.gelu(h @ blk["mlp"]["fc"]["w"].astype(dtype)
                            + blk["mlp"]["fc"]["b"].astype(dtype))
            x = x + (h @ blk["mlp"]["proj"]["w"].astype(dtype)
                     + blk["mlp"]["proj"]["b"].astype(dtype))
        return x

    pipe_cfg = PipelineConfig(n_stages, n_microbatches, axis_name=axis,
                              schedule=schedule, data_axis=data_axis,
                              n_virtual=max(1, n_virtual))

    def stack_blocks(params):
        # list of layer pytrees -> [n_chunks, per_stage, ...] leading dims
        blocks = params["blocks"]
        stages = []
        for s in range(n_chunks):
            chunk = blocks[s * per_stage:(s + 1) * per_stage]
            stages.append(jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunk))
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stages)

    def embed(wte, wpe, tokens_mb):
        seq = tokens_mb.shape[-1]
        return wte[tokens_mb].astype(dtype) \
            + wpe.astype(dtype)[None, None, :seq]

    def head_loss(x_mb, targets_mb, hp):
        x = _layernorm(x_mb, hp["ln_f"]["g"], hp["ln_f"]["b"])
        logits = x.astype(jnp.float32) @ hp["wte"].T
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, targets_mb[..., None],
                                    axis=-1).mean()

    if schedule == "1f1b":
        pipe_grad = spmd_pipeline_grad(stage_fn, head_loss, mesh, pipe_cfg,
                                       aux=True)

        def loss_and_grads(params, tokens_mb, targets_mb):
            x_mb, emb_vjp = jax.vjp(
                lambda wte, wpe: embed(wte, wpe, tokens_mb),
                params["wte"], params["wpe"])
            hp = {"ln_f": params["ln_f"], "wte": params["wte"]}
            loss, sgrads, dx_mb, dhp = pipe_grad(
                stack_blocks(params), x_mb, targets_mb, hp)
            dwte_emb, dwpe = emb_vjp(dx_mb)
            dblocks = [
                jax.tree_util.tree_map(lambda l: l[s][i], sgrads)
                for s in range(n_chunks) for i in range(per_stage)]
            grads = {"wte": dwte_emb + dhp["wte"], "wpe": dwpe,
                     "ln_f": dhp["ln_f"], "blocks": dblocks}
            return loss, grads
    else:
        pipe = spmd_pipeline(stage_fn, mesh, pipe_cfg)

        def forward(params, tokens_mb):
            # tokens_mb: [M, mb, seq]
            x = embed(params["wte"], params["wpe"], tokens_mb)
            x = pipe(stack_blocks(params), x)
            x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
            return x.astype(jnp.float32) @ params["wte"].T

        def loss_fn(params, tokens_mb, targets_mb):
            logits = forward(params, tokens_mb)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, targets_mb[..., None],
                                        axis=-1).mean()

        def loss_and_grads(params, tokens_mb, targets_mb):
            return jax.value_and_grad(loss_fn)(params, tokens_mb, targets_mb)

    def init_state(key):
        params = gpt_init(cfg, key)
        return (params, adam_init(params))

    def train_step(state, tokens_mb, targets_mb):
        params, opt = state
        loss, grads = loss_and_grads(params, tokens_mb, targets_mb)
        new_params, new_opt = adam_update(params, grads, opt, lr=lr)
        return (new_params, new_opt), loss

    return train_step, init_state
