"""ViT-B/16-style vision transformer (BASELINE.json config: "ViT-B/16
auto-shard vs manual FSDP").  Patchify is a conv; the encoder reuses
pre-norm transformer blocks."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp

from .optim import adam_init, adam_update


@dataclass
class ViTConfig:
    image: int = 224
    patch: int = 16
    dim: int = 768
    heads: int = 12
    layers: int = 12
    classes: int = 1000
    dtype: str = "float32"

    @staticmethod
    def b16(**kw):
        return ViTConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(image=32, patch=8, dim=32, heads=4, layers=2, classes=10)
        base.update(kw)
        return ViTConfig(**base)

    @property
    def n_patches(self):
        return (self.image // self.patch) ** 2


def _linear_init(key, n_in, n_out):
    return {"w": jax.random.normal(key, (n_in, n_out)) / math.sqrt(n_in),
            "b": jnp.zeros((n_out,))}


def vit_init(cfg: ViTConfig, key) -> Dict:
    keys = jax.random.split(key, 3 + cfg.layers)
    params = {
        "patch": jax.random.normal(
            keys[0], (cfg.patch, cfg.patch, 3, cfg.dim))
            * math.sqrt(2.0 / (cfg.patch * cfg.patch * 3)),
        "pos": jax.random.normal(keys[1], (cfg.n_patches + 1, cfg.dim)) * 0.02,
        "cls": jnp.zeros((cfg.dim,)),
        "blocks": [],
        "ln_f": {"g": jnp.ones((cfg.dim,)), "b": jnp.zeros((cfg.dim,))},
        "head": _linear_init(keys[2], cfg.dim, cfg.classes),
    }
    for i in range(cfg.layers):
        bk = jax.random.split(keys[3 + i], 4)
        params["blocks"].append({
            "ln1": {"g": jnp.ones((cfg.dim,)), "b": jnp.zeros((cfg.dim,))},
            "qkv": _linear_init(bk[0], cfg.dim, 3 * cfg.dim),
            "proj": _linear_init(bk[1], cfg.dim, cfg.dim),
            "ln2": {"g": jnp.ones((cfg.dim,)), "b": jnp.zeros((cfg.dim,))},
            "fc": _linear_init(bk[2], cfg.dim, 4 * cfg.dim),
            "fc2": _linear_init(bk[3], 4 * cfg.dim, cfg.dim),
        })
    return params


def _layernorm(x, g, b, eps=1e-5):
    mu = x.mean(axis=-1, keepdims=True)
    var = x.var(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def _mha(x, blk, heads):
    b, t, d = x.shape
    hd = d // heads
    qkv = x @ blk["qkv"]["w"] + blk["qkv"]["b"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def sh(y):
        return y.reshape(b, t, heads, hd).transpose(0, 2, 1, 3)

    q, k, v = sh(q), sh(k), sh(v)
    att = jax.nn.softmax(
        jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd), axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, d)
    return out @ blk["proj"]["w"] + blk["proj"]["b"]


def vit_apply(params, cfg: ViTConfig, images):
    """images: [batch, H, W, 3] -> logits [batch, classes]."""
    b = images.shape[0]
    x = jax.lax.conv_general_dilated(
        images, params["patch"], (cfg.patch, cfg.patch), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    x = x.reshape(b, -1, cfg.dim)
    cls = jnp.broadcast_to(params["cls"], (b, 1, cfg.dim))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"][None]
    for blk in params["blocks"]:
        x = x + _mha(_layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"]),
                     blk, cfg.heads)
        h = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        h = jax.nn.gelu(h @ blk["fc"]["w"] + blk["fc"]["b"])
        x = x + h @ blk["fc2"]["w"] + blk["fc2"]["b"]
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return x[:, 0] @ params["head"]["w"] + params["head"]["b"]


def make_vit_train_step(cfg: ViTConfig, lr=1e-4):
    def init_state(key):
        params = vit_init(cfg, key)
        return (params, adam_init(params))

    def train_step(state, images, labels):
        params, opt = state

        def loss_fn(p):
            logits = vit_apply(p, cfg, images)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_opt = adam_update(params, grads, opt, lr=lr)
        return (new_params, new_opt), loss

    return train_step, init_state
