"""Model zoo used by tests, examples, and benchmarks.

Pure-functional jax models (init/apply pairs) mirroring the reference's
benchmark model set (benchmark/torch/model/: GPT, wide-ResNet, GAT;
benchmark/bench_case.py:5-25 for the headline configs).  Written TPU-first:
bfloat16-friendly, static shapes, no data-dependent control flow.
"""

from .mlp import mlp_init, mlp_apply, make_mlp_train_step  # noqa: F401
from .gpt import (GPTConfig, gpt_init, gpt_apply,  # noqa: F401
                  make_gpt_train_step)
from .gpt import (init_kv_cache as gpt_init_kv_cache,  # noqa: F401
                  gpt_prefill, gpt_prefill_chunk, gpt_decode_step)
from .resnet import resnet_init, resnet_apply, make_resnet_train_step  # noqa: F401
from .optim import adam_init, adam_update, sgd_update  # noqa: F401
from .llama import (LlamaConfig, llama_init, llama_apply,  # noqa: F401
                    make_llama_train_step)
from .llama import (init_kv_cache as llama_init_kv_cache,  # noqa: F401
                    llama_prefill, llama_prefill_chunk, llama_decode_step)
from .vit import ViTConfig, vit_init, vit_apply, make_vit_train_step  # noqa: F401
from .gat import GATConfig, gat_init, gat_apply, make_gat_train_step  # noqa: F401
