"""`FleetRouter`: N `GenerationSession` replicas behind one submit().

Scales serve/ from one host to a fleet without touching the bitwise
spine: every replica runs the same params and the same compiled programs,
prefix restore is bitwise-equal to recompute, and greedy continuation is
a pure function of the token prefix — so WHICH replica serves a request
(or whether its prefill ran on a different replica, or it migrated
mid-stream during a drain) never changes a single output token.

Routing is a scored policy over live signals:

  * **prefix-cache affinity** — `PrefixCache.peek` (non-mutating) probes
    how many prompt tokens each replica's trie already holds; requests
    sharing a system prefix converge on the replica that warmed it and
    skip that prefill entirely;
  * **occupancy** — free decode-slot fraction from the session's
    `queue_depth`, so affinity never piles everything onto one replica;
  * **breaker/health** — a replica whose `CircuitBreaker` is OPEN is
    ineligible (routing to it anyway is the FLEET001 error);
  * **consistent-hash fallback** — a cold prefix (zero affinity
    everywhere) routes by `HashRing` over its page-aligned prefix, so
    identical cold prefixes co-locate and BUILD affinity instead of
    scattering.

Prefill/decode disaggregation: with dedicated prefill replicas, the
page-aligned prompt prefix runs chunked prefill there, the committed
pages hand off through a `KVTransport` (sha256 page manifest, FLEET002),
and the decode replica restores them on admission — computing only the
unaligned tail.  Elastic drain: `drain(rid)` stops new admits, keeps
stepping the replica until in-flight work retires (other replicas never
stall — zero downtime), migrates its hot trie pages to the survivors,
and audits the emptied trie for orphaned pins (FLEET003).  `evacuate`
mode retires live decodes immediately with partial ids and the router
resubmits prompt+partial elsewhere, bitwise-seamlessly.

Fault tolerance (fleet/failover.py + fleet/health.py): a replica whose
`step()` raises — or that a `HealthMonitor` probe declares DEAD (alive
but making no progress with live work) — is removed on the spot and its
stranded requests resume on survivors from their `ResumeDescriptor`s:
the router syncs each request's already-emitted ids from the live
session after every successful step (what a streaming client has
already received), so recovery resubmits prompt+ids with the remaining
budget and the bitwise spine guarantees the continuation is
token-for-token identical.  Every resume is audited first (FLEET005);
routing a request to a DEAD replica is the FLEET004 error.  A request
that crashes `quarantine_after` distinct replicas is poison — its
future fails with `PoisonRequestError` instead of rolling through the
fleet.  Re-registering a crashed replica id via `add_replica` is the
revive operation (the chaos drill's schedule does exactly that).
"""

from __future__ import annotations

import logging
import random
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from easydist_tpu.resilience.breaker import OPEN, CircuitBreaker
from easydist_tpu.serve.admission import (AdmissionController,
                                          CircuitOpenError,
                                          DeadlineExceededError,
                                          RequestTooLargeError)
from easydist_tpu.serve.batcher import select_bucket
from easydist_tpu.serve.metrics import ServeMetrics

from .failover import PoisonRequestError, ResumeDescriptor
from .hashring import HashRing, prefix_hash_key
from .health import DEAD, HealthConfig, HealthMonitor
from .transport import InProcessTransport, KVTransport, TransportError

logger = logging.getLogger(__name__)

__all__ = ["FleetConfig", "FleetRouter", "Replica"]


@dataclass(frozen=True)
class FleetConfig:
    """Routing policy knobs.

    affinity_weight / occupancy_weight: the scored policy is
        w_aff * (cached prefix tokens / prompt tokens)
        + w_occ * (free decode-slot fraction); affinity dominating means
        a warm trie wins unless the replica is nearly full.
    policy: "affinity" (scored + hash fallback) or "random" (uniform —
        the bench's comparison arm, never the production setting).
    vnodes: virtual points per replica on the consistent-hash ring.
    max_queue: fleet-wide bound on live requests; submits beyond it
        raise QueueFullError (the admission layer's check).
    default_deadline_ms: deadline stamped on submits that pass none.
    seed: rng seed for the "random" policy (deterministic benches).
    probe_interval_ms / miss_budget: HealthMonitor knobs — min wall-clock
        between liveness probe rounds (0 probes every step) and
        consecutive missed probes before a replica is declared DEAD.
    quarantine_after: distinct replicas one request may crash before its
        future fails with PoisonRequestError instead of resubmitting.
    handoff_retries / handoff_backoff_ms / handoff_deadline_ms: transport
        send_pages retry policy for prefill handoff and drain migration
        (deadline None = retries alone bound the attempt count).
    migrate_wave_bytes: byte ceiling per drain-migration WAVE — hot-page
        paths are batched by the reshard chunk planner (reshard.chunk_waves)
        so in-flight migration bytes stay bounded regardless of how much
        warm trie a draining replica holds (0 = one unbounded wave).
    """
    affinity_weight: float = 2.0
    occupancy_weight: float = 1.0
    policy: str = "affinity"
    vnodes: int = 64
    max_queue: int = 1024
    default_deadline_ms: Optional[float] = None
    seed: int = 0
    probe_interval_ms: float = 0.0
    miss_budget: int = 3
    quarantine_after: int = 3
    handoff_retries: int = 2
    handoff_backoff_ms: float = 5.0
    handoff_deadline_ms: Optional[float] = None
    migrate_wave_bytes: int = 8 << 20

    def __post_init__(self):
        if self.policy not in ("affinity", "random"):
            raise ValueError(f"unknown routing policy {self.policy!r}")
        if self.vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {self.vnodes}")
        if self.affinity_weight < 0 or self.occupancy_weight < 0:
            raise ValueError("routing weights must be >= 0")
        if self.quarantine_after < 1:
            raise ValueError(
                f"quarantine_after must be >= 1, got "
                f"{self.quarantine_after}")
        if self.handoff_retries < 0:
            raise ValueError("handoff_retries must be >= 0")
        if self.migrate_wave_bytes < 0:
            raise ValueError("migrate_wave_bytes must be >= 0")


@dataclass
class Replica:
    """One registered session + its health surface."""
    replica_id: str
    session: object                      # GenerationSession
    breaker: Optional[CircuitBreaker] = None
    role: str = "decode"                 # "decode" | "prefill"

    def eligible(self) -> bool:
        return (not self.session.is_draining
                and (self.breaker is None or self.breaker.state != OPEN))


@dataclass
class _Inflight:
    """Router-side record of one request across replica hops.

    `resume` is the live ResumeDescriptor: its `ids` mirror what the
    serving session has emitted so far (synced after every successful
    step), which is exactly the recovery state a crash needs.
    `hop_base` is the `ids` snapshot at the current submission — the
    tokens emitted by PREVIOUS hops, which the current session's partial
    output concatenates onto."""
    resume: ResumeDescriptor
    future: Future                       # the caller's future
    hop_base: List[int] = field(default_factory=list)
    replica_id: Optional[str] = None
    inner: Optional[Future] = None       # current session future
    deadline_t: Optional[float] = None
    t_submit: float = 0.0

    @property
    def request_id(self) -> int:
        return self.resume.request_id


@dataclass
class _Handoff:
    """One disaggregated prefill awaiting page transfer."""
    request_id: int
    prefill_replica: str
    decode_replica: str
    aligned: List[int]                   # page-aligned prompt prefix
    inner: Future                        # prefill session future


class FleetRouter:
    """Multi-replica serving front: route, disaggregate, drain."""

    def __init__(self, replicas: Sequence, *,
                 prefill_replicas: Sequence = (),
                 config: Optional[FleetConfig] = None,
                 transport: Optional[KVTransport] = None,
                 health: Optional[HealthMonitor] = None):
        self.config = config or FleetConfig()
        self.transport = transport or InProcessTransport()
        self.health = health or HealthMonitor(HealthConfig(
            probe_interval_ms=self.config.probe_interval_ms,
            miss_budget=self.config.miss_budget))
        self._replicas: Dict[str, Replica] = {}
        self._ring = HashRing(vnodes=self.config.vnodes)
        self._prefill_ring = HashRing(vnodes=self.config.vnodes)
        for sess in replicas:
            self.add_replica(sess, role="decode")
        for sess in prefill_replicas:
            self.add_replica(sess, role="prefill")
        if not any(r.role == "decode" for r in self._replicas.values()):
            raise ValueError("fleet needs at least one decode replica")
        self.admission = AdmissionController(
            self.config.max_queue,
            default_deadline_ms=self.config.default_deadline_ms)
        self.metrics = ServeMetrics(replica_id="fleet")
        self._rng = random.Random(self.config.seed)
        self._inflight: Dict[int, _Inflight] = {}
        self._handoffs: List[_Handoff] = []
        self._next_request_id = 0
        # audit surfaces: FLEET001/004 read the decision log, FLEET003
        # the drain log; all bounded so a long-lived router stays O(1)
        self.decision_log: List[Dict[str, object]] = []
        self.drain_log: List[Dict[str, object]] = []
        self.crash_log: List[Dict[str, object]] = []
        self._log_cap = 1024
        # layer-12 conformance surface: the request-lifecycle event
        # stream `analyze.modelcheck.replay_router_protocol` replays
        # against the RouterSpec (PROTO003).  Bounded like the other
        # logs; `protocol_events_dropped` counts truncation so a capped
        # log is never mistaken for a complete (and seemingly drifting)
        # protocol history
        self.protocol_log: List[Dict[str, object]] = []
        self.protocol_events_dropped = 0
        self._proto_cap = 4096

    # ------------------------------------------------------------ replicas
    def add_replica(self, session, role: str = "decode") -> Replica:
        rid = session.replica_id
        if not rid:
            raise ValueError("fleet sessions need a replica_id")
        if rid in self._replicas:
            raise ValueError(f"duplicate replica_id {rid!r}")
        cfg = session.config
        breaker = None
        if cfg.breaker_failure_threshold > 0:
            breaker = CircuitBreaker(
                failure_threshold=cfg.breaker_failure_threshold,
                cooldown_s=cfg.breaker_cooldown_ms / 1e3,
                p99_threshold_s=(cfg.breaker_p99_threshold_ms / 1e3
                                 if cfg.breaker_p99_threshold_ms is not None
                                 else None),
                min_samples=cfg.breaker_min_samples,
                p99=lambda m=session.metrics: m.execute.percentile(99),
                replica_id=rid)
        rep = Replica(replica_id=rid, session=session, breaker=breaker,
                      role=role)
        self._replicas[rid] = rep
        (self._ring if role == "decode" else self._prefill_ring).add(rid)
        # re-registering a previously-crashed id is the REVIVE operation:
        # clear its DEAD tombstone so routing sees the fresh session
        self.health.revive(rid)
        return rep

    def replica(self, replica_id: str) -> Replica:
        return self._replicas[replica_id]

    def _decode_replicas(self) -> List[Replica]:
        return [r for r in self._replicas.values() if r.role == "decode"]

    def _prefill_replicas(self) -> List[Replica]:
        return [r for r in self._replicas.values() if r.role == "prefill"]

    def _eligible(self, rep: Replica) -> bool:
        """Replica-level eligibility (draining/breaker) AND health: a
        DEAD replica is ineligible exactly like an OPEN breaker."""
        return rep.eligible() \
            and self.health.state(rep.replica_id) != DEAD

    # ------------------------------------------------------------- routing
    def _aligned_prefix(self, prompt: Sequence[int]) -> List[int]:
        """Longest trie-page-aligned strict-prefix of `prompt` — the
        affinity/hash identity AND the disaggregated-prefill unit (the
        unaligned tail plus at least one token always prefills on the
        decode replica, matching the trie's max_tokens=len-1 cap)."""
        chunk = None
        for rep in self._decode_replicas():
            chunk = rep.session.bucket_chunk(prompt)
            if chunk:
                break
        if not chunk:
            return list(prompt)
        aligned = ((len(prompt) - 1) // chunk) * chunk
        return list(prompt[:aligned]) if aligned else list(prompt)

    def _route(self, prompt: Sequence[int],
               request_id: int) -> Replica:
        """Pick the decode replica; logs the decision for FLEET001/004."""
        eligible = [r for r in self._decode_replicas()
                    if self._eligible(r)]
        if not eligible:
            waits = [r.breaker.retry_after_s()
                     for r in self._decode_replicas() if r.breaker]
            raise CircuitOpenError(
                "no eligible decode replica (all draining or circuit-"
                "open)", retry_after_s=max([0.0] + waits))
        if self.config.policy == "random":
            chosen = self._rng.choice(eligible)
            affinity = 0
        else:
            aff = {r.replica_id: r.session.prefix_affinity(prompt)
                   for r in eligible}
            if max(aff.values()) == 0:
                key = prefix_hash_key(self._aligned_prefix(prompt))
                rid = self._ring.route(
                    key, eligible=[r.replica_id for r in eligible])
                chosen = self._replicas[rid] if rid else eligible[0]
            else:
                def score(r: Replica) -> Tuple[float, int, str]:
                    occ_free = 1.0 - (
                        r.session.queue_depth
                        / max(1, r.session.config.max_decode_slots))
                    s = (self.config.affinity_weight
                         * aff[r.replica_id] / len(prompt)
                         + self.config.occupancy_weight
                         * max(0.0, occ_free))
                    # deterministic tie-break: least loaded, then id
                    return (-s, r.session.queue_depth, r.replica_id)
                chosen = min(eligible, key=score)
            affinity = aff.get(chosen.replica_id, 0) \
                if self.config.policy == "affinity" else 0
        self._log(self.decision_log, {
            "request_id": request_id,
            "replica_id": chosen.replica_id,
            "breaker_state": (chosen.breaker.state if chosen.breaker
                              else "closed"),
            "draining": chosen.session.is_draining,
            "health": self.health.state(chosen.replica_id),
            "affinity_tokens": affinity,
            "prompt_tokens": len(prompt),
            "policy": self.config.policy,
        })
        if affinity:
            self.metrics.inc("routed_warm")
        self.metrics.inc("routed")
        return chosen

    def _log(self, log: List, entry: Dict) -> None:
        log.append(entry)
        del log[:-self._log_cap]

    def _proto(self, request_id: int, event: str) -> None:
        """One request-lifecycle protocol event (layer-12 conformance)."""
        self.protocol_log.append(
            {"request_id": request_id, "event": event})
        if len(self.protocol_log) > self._proto_cap:
            dropped = len(self.protocol_log) - self._proto_cap
            del self.protocol_log[:dropped]
            self.protocol_events_dropped += dropped

    def transitions(self) -> List[Dict[str, object]]:
        """The protocol event stream, oldest first — the surface
        `replay_router_protocol` (PROTO003) validates against the
        RouterSpec.  Check `protocol_events_dropped` before treating it
        as a complete history."""
        return list(self.protocol_log)

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Route one prompt into the fleet; the returned future resolves
        to the same {"ids", "finish_reason"} a single session produces,
        plus "replica_id" (the LAST replica that decoded it)."""
        prompt = [int(t) for t in prompt_ids]
        any_fit = any(
            select_bucket(len(prompt) + 1,
                          r.session.config.decode_buckets) is not None
            for r in self._decode_replicas())
        if prompt and not any_fit:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens fits no replica's decode "
                f"buckets")
        self.admission.check_depth(self.total_queue_depth)
        deadline_t = self.admission.resolve_deadline(deadline_ms)
        rid = self._next_request_id
        self._next_request_id += 1
        rec = _Inflight(resume=ResumeDescriptor(
                            request_id=rid, prompt=prompt,
                            max_new=max_new_tokens, eos_id=eos_id),
                        future=Future(), deadline_t=deadline_t,
                        t_submit=time.perf_counter())
        chosen = self._route(prompt, rid)
        # "admitted" lands only once a route exists: a submit() that
        # raises CircuitOpenError never entered the protocol, so the
        # zero-drop replay must not expect a terminal for it
        self._proto(rid, "admitted")
        self._inflight[rid] = rec
        if not self._start_disaggregated(rec, chosen):
            rec.replica_id = chosen.replica_id
            self._proto(rid, "routed")
            rec.inner = chosen.session.submit(
                prompt, max_new_tokens=max_new_tokens, eos_id=eos_id)
        else:
            self._proto(rid, "handoff_started")
        self.metrics.inc("requests_submitted")
        self.metrics.set_gauge("queue_depth", self.total_queue_depth)
        self.metrics.set_gauge("router_inflight", len(self._inflight))
        return rec.future

    def _start_disaggregated(self, rec: _Inflight,
                             decode_rep: Replica) -> bool:
        """Run the page-aligned prefix on a dedicated prefill replica when
        that saves decode-side prefill; returns False to submit directly
        (no prefill tier, prompt under one page, decode trie already
        warm, or page sizes disagree across tiers)."""
        prefill = [r for r in self._prefill_replicas()
                   if self._eligible(r)]
        if not prefill:
            return False
        prompt = rec.resume.prompt
        aligned = self._aligned_prefix(prompt)
        chunk = decode_rep.session.bucket_chunk(prompt)
        if not chunk or len(aligned) < chunk \
                or len(aligned) == len(prompt):
            return False
        if decode_rep.session.prefix_affinity(prompt) >= len(aligned):
            return False  # decode trie already holds everything aligned
        src = prefill[0]
        if len(prefill) > 1:
            rid = self._prefill_ring.route(
                prefix_hash_key(aligned),
                eligible=[r.replica_id for r in prefill])
            src = self._replicas[rid] if rid else prefill[0]
        if src.session.bucket_chunk(aligned) != chunk:
            return False  # page sizes disagree; handoff would be refused
        rec.replica_id = decode_rep.replica_id
        inner = src.session.submit(aligned, max_new_tokens=1)
        self._handoffs.append(_Handoff(
            request_id=rec.request_id, prefill_replica=src.replica_id,
            decode_replica=decode_rep.replica_id, aligned=aligned,
            inner=inner))
        self.metrics.inc("prefill_handoffs")
        return True

    @property
    def total_queue_depth(self) -> int:
        return sum(r.session.queue_depth
                   for r in self._replicas.values()) + len(self._handoffs)

    @property
    def inflight_count(self) -> int:
        """Router-tracked in-flight requests — the observer-safe read
        the autoscaler's MetricsView uses (PROTO004: observers never
        reach `_inflight` directly)."""
        return len(self._inflight)

    def live_decode_snapshot(self, eligible_only: bool = False
                             ) -> List[Dict[str, object]]:
        """Read-only per-replica view of the non-draining decode tier
        for observer code (autoscaler metrics/drain planning).  This is
        the snapshot-only-metrics contract layer 12 enforces: observers
        consume value snapshots like this one, never the router's live
        `_replicas`/`_inflight` structures — those become remote state
        the moment replicas live in another process."""
        out: List[Dict[str, object]] = []
        for r in self._decode_replicas():
            if r.session.is_draining:
                continue
            if eligible_only and not self._eligible(r):
                continue
            out.append({
                "replica_id": r.replica_id,
                "queue_depth": int(r.session.queue_depth),
                "hot_pools": len(getattr(r.session, "_pools", None)
                                 or ()),
            })
        return out

    # -------------------------------------------------------------- driving
    def step(self) -> int:
        """One fleet round: step EVERY replica (draining ones included —
        their in-flight work retires while the others keep serving; that
        is the zero-downtime property), sync per-request progress into
        resume descriptors, run the health probe, then harvest handoffs,
        replica hops, completions, and finished drains.  A replica whose
        step raises — or that the probe declares DEAD — fails over on
        the spot (`_on_replica_crash`): the fleet keeps serving.
        Returns decode tokens generated across the fleet this round."""
        tokens = 0
        for rep in list(self._replicas.values()):
            sess = rep.session
            had_work = not sess.is_drained
            try:
                tokens += sess.step()
            except Exception as e:
                if rep.breaker is not None:
                    rep.breaker.record_failure()
                self._on_replica_crash(rep, e)
                continue
            if had_work and rep.breaker is not None:
                rep.breaker.record_success()
            self._sync_progress(rep)
        for rid in self.health.probe(list(self._replicas.values())):
            # alive-but-wedged: no counter progress with live work for
            # miss_budget consecutive probes — treat exactly as a crash
            self._on_replica_crash(
                self._replicas[rid],
                RuntimeError(f"health probe declared {rid} dead "
                             f"(no progress with live work)"))
        self._poll_handoffs()
        self._poll_inflight()
        self._poll_drains()
        self._gc_inflight()
        self.metrics.set_gauge("queue_depth", self.total_queue_depth)
        self.metrics.set_gauge("router_inflight", len(self._inflight))
        return tokens

    def _sync_progress(self, rep: Replica) -> None:
        """Mirror the session's per-request emitted ids into the router's
        resume descriptors — the state a streaming client has already
        received, and therefore the exact prefix recovery must continue
        from if this replica dies before its next step."""
        live = {id(e["future"]): e
                for e in rep.session.snapshot_inflight()}
        for rec in self._inflight.values():
            if rec.replica_id != rep.replica_id or rec.inner is None:
                continue
            entry = live.get(id(rec.inner))
            if entry is not None:
                rec.resume.ids = rec.hop_base + list(entry["ids"])

    # ------------------------------------------------------------- failover
    def _on_replica_crash(self, rep: Replica, exc: Exception) -> None:
        """Remove a dead replica and recover its stranded work: pending
        prefill handoffs fall back to direct prefill, every in-flight
        request resumes on a survivor from its ResumeDescriptor (or is
        quarantined once it has crashed `quarantine_after` distinct
        replicas).  The dead session's trie pages are NOT migrated —
        unlike a drain, nothing it holds can be trusted."""
        rid = rep.replica_id
        logger.error("replica %s crashed (%s); failing over", rid, exc)
        self.health.mark_dead(rid, reason=str(exc))
        (self._ring if rep.role == "decode"
         else self._prefill_ring).remove(rid)
        self._replicas.pop(rid, None)
        self.metrics.inc("replica_crashes")
        self._log(self.crash_log, {
            "replica_id": rid, "role": rep.role, "error": repr(exc)})
        for h in list(self._handoffs):
            if h.prefill_replica != rid:
                continue  # dead decode targets re-route at harvest
            self._handoffs.remove(h)
            rec = self._inflight.get(h.request_id)
            if rec is not None:
                self.metrics.inc("handoff_fallbacks")
                self._recover_request(rec, rid)
        for rec in list(self._inflight.values()):
            if rec.replica_id != rid:
                continue
            if rec.inner is not None and rec.inner.done():
                continue  # retired before the crash; harvests normally
            if any(h.request_id == rec.request_id
                   for h in self._handoffs):
                continue  # prefill still running; harvest re-routes
            self._recover_request(rec, rid)

    def _recover_request(self, rec: _Inflight, crashed_rid: str) -> None:
        """Quarantine-or-resume one request stranded by a crash."""
        rec.resume.crashed_on.add(crashed_rid)
        if len(rec.resume.crashed_on) >= self.config.quarantine_after:
            del self._inflight[rec.request_id]
            self._proto(rec.request_id, "quarantined")
            rec.future.set_exception(PoisonRequestError(
                rec.request_id, rec.resume.crashed_on))
            self.metrics.inc("requests_quarantined")
            self.metrics.inc("requests_failed")
            logger.error("request %d quarantined after crashing "
                         "replicas %s", rec.request_id,
                         sorted(rec.resume.crashed_on))
            return
        self._proto(rec.request_id, "recovered")
        self._resubmit(rec)
        self.metrics.inc("requests_recovered")

    def _resubmit(self, rec: _Inflight) -> None:
        """Continue `rec` on a surviving replica from its descriptor:
        resubmit prompt + emitted ids with the remaining budget.  Audited
        first (FLEET005) — a descriptor that would change tokens must
        fail loudly, never resume silently wrong."""
        desc = rec.resume
        resume_prompt = desc.resume_prompt()
        self._audit_resume(desc, resume_prompt)
        try:
            nxt = self._route(resume_prompt, rec.request_id)
        except CircuitOpenError as e:
            self._inflight.pop(rec.request_id, None)
            self._proto(rec.request_id, "failed")
            rec.future.set_exception(e)
            self.metrics.inc("requests_failed")
            return
        self._proto(rec.request_id, "routed")
        rec.replica_id = nxt.replica_id
        rec.hop_base = list(desc.ids)
        rec.inner = nxt.session.submit(
            resume_prompt, max_new_tokens=desc.remaining(),
            eos_id=desc.eos_id)

    def _audit_resume(self, desc: ResumeDescriptor,
                      resume_prompt: List[int]) -> None:
        try:
            from easydist_tpu.analyze import check_resume_descriptor

            check_resume_descriptor(
                desc.as_dict(), resume_prompt,
                node=f"resume[{desc.request_id}]")
        except ImportError:
            pass

    def _gc_inflight(self) -> None:
        """Bound `_inflight`: drop externally-cancelled entries, fail
        deadline-expired ones, and resume orphans whose replica vanished
        without a crash record (defense in depth — the crash path
        normally resubmits immediately)."""
        now = time.monotonic()
        for rid, rec in list(self._inflight.items()):
            if rec.future.done():
                # only an external cancel/resolution leaves a done future
                # tracked; the router deletes before resolving otherwise
                del self._inflight[rid]
                self._proto(rid, "failed")
                self.metrics.inc("inflight_gc")
                continue
            if rec.deadline_t is not None and now > rec.deadline_t:
                del self._inflight[rid]
                self._proto(rid, "failed")
                rec.future.set_exception(DeadlineExceededError(
                    f"request {rid} exceeded its deadline in flight"))
                self.metrics.inc("requests_timed_out")
                self.metrics.inc("requests_failed")
                continue
            if rec.inner is None and rec.replica_id is not None \
                    and rec.replica_id not in self._replicas \
                    and not any(h.request_id == rid
                                for h in self._handoffs):
                self.metrics.inc("inflight_orphans_recovered")
                self._proto(rid, "recovered")
                self._resubmit(rec)

    def _poll_handoffs(self) -> None:
        for h in list(self._handoffs):
            if not h.inner.done():
                continue
            self._handoffs.remove(h)
            rec = self._inflight.get(h.request_id)
            if rec is None:
                continue
            result = h.inner.result()
            prompt = rec.resume.prompt
            dst = self._replicas.get(h.decode_replica)
            src = self._replicas.get(h.prefill_replica)
            handed_off = False
            if result["finish_reason"] != "length":
                # prefill replica was evacuated under us: nothing
                # committed for sure — decode replica prefills from zero
                logger.warning("prefill handoff %s interrupted (%s); "
                               "falling back to direct prefill",
                               h.request_id, result["finish_reason"])
            elif src is not None and dst is not None:
                path = src.session.export_prefix_path(h.aligned)
                cfg = self.config
                try:
                    moved = self.transport.send_pages(
                        path, dst.session, prompt,
                        src=h.prefill_replica, dst=h.decode_replica,
                        deadline_s=(cfg.handoff_deadline_ms / 1e3
                                    if cfg.handoff_deadline_ms is not None
                                    else None),
                        retries=cfg.handoff_retries,
                        backoff_s=cfg.handoff_backoff_ms / 1e3)
                    self.metrics.inc("pages_handed_off", moved)
                    handed_off = True
                except TransportError as e:
                    # permanent transport failure is never fatal to the
                    # REQUEST: the decode replica prefills from zero and
                    # parity holds (restore == recompute)
                    logger.warning(
                        "page handoff %s->%s failed permanently (%s); "
                        "falling back to direct prefill",
                        h.prefill_replica, h.decode_replica, e)
                    self.metrics.inc("handoff_transport_failures")
            self._proto(rec.request_id,
                        "handoff_committed" if handed_off
                        else "handoff_fallback")
            if dst is None or not self._eligible(dst):
                # decode target crashed or started draining while
                # prefill ran: re-route; restore == recompute keeps
                # parity either way
                try:
                    dst = self._route(prompt, rec.request_id)
                except CircuitOpenError as e:
                    del self._inflight[rec.request_id]
                    self._proto(rec.request_id, "failed")
                    rec.future.set_exception(e)
                    self.metrics.inc("requests_failed")
                    continue
                self._proto(rec.request_id, "routed")
            rec.replica_id = dst.replica_id
            rec.inner = dst.session.submit(
                prompt, max_new_tokens=rec.resume.max_new,
                eos_id=rec.resume.eos_id)

    def _poll_inflight(self) -> None:
        for rid, rec in list(self._inflight.items()):
            if rec.inner is None or not rec.inner.done():
                continue
            result = rec.inner.result()
            if result["finish_reason"] == "evacuated":
                # mid-stream migration: greedy continuation is a pure
                # function of the prefix, so prompt+partial resumed on
                # any replica concatenates bitwise-identically
                rec.resume.ids = rec.hop_base + list(result["ids"])
                self._proto(rid, "migrated")
                self._resubmit(rec)
                self.metrics.inc("migrations")
                continue
            del self._inflight[rid]
            self._proto(rid, "completed")
            rec.future.set_result({
                "ids": rec.hop_base + list(result["ids"]),
                "finish_reason": result["finish_reason"],
                "replica_id": rec.replica_id,
            })
            self.metrics.inc("requests_completed")
            self.metrics.observe(
                "e2e", time.perf_counter() - rec.t_submit)

    def _poll_drains(self) -> None:
        for rep in list(self._replicas.values()):
            if not rep.session.is_draining or not rep.session.is_drained:
                continue
            if any(h.prefill_replica == rep.replica_id
                   or h.decode_replica == rep.replica_id
                   for h in self._handoffs):
                continue  # let pending handoffs clear first
            self._finish_drain(rep)

    # --------------------------------------------------------------- drain
    def drain(self, replica_id: str, mode: str = "graceful") -> None:
        """Begin removing one replica with zero dropped requests.

        "graceful": stop new admits, keep stepping until its in-flight
        decodes retire naturally.  "evacuate": retire live work NOW with
        partial ids (SIGTERM-grace semantics — resilience/preempt.py);
        the inflight poller resubmits each prompt+partial elsewhere.
        Either way the replica's hot trie pages migrate to the survivors
        before it is removed (next step() after it empties)."""
        rep = self._replicas[replica_id]
        (self._ring if rep.role == "decode"
         else self._prefill_ring).remove(replica_id)
        if mode == "evacuate":
            rep.session.evacuate()
        elif mode == "graceful":
            rep.session.drain(wait=False)
        else:
            raise ValueError(f"unknown drain mode {mode!r}")
        self.metrics.inc("drains_started")

    def _finish_drain(self, rep: Replica) -> None:
        pages = rep.session.export_hot_pages()
        survivors = [r for r in self._decode_replicas()
                     if r.replica_id != rep.replica_id
                     and self._eligible(r)]
        cfg = self.config
        migrated = 0
        for bucket, paths in pages.items():
            for dst in survivors:
                # wave-batched through the shared reshard chunk planner:
                # in-flight bytes stay under migrate_wave_bytes however
                # warm the draining trie is.  Each path is still a
                # manifest-verified + retried handoff (FLEET002);
                # migration is best-effort — a path that fails
                # permanently is dropped (survivors recompute the prefix
                # on demand), never half-committed
                def _drop(i, e, _dst=dst):
                    logger.warning(
                        "drain migration %s->%s dropped a path: %s",
                        rep.replica_id, _dst.replica_id, e)
                    self.metrics.inc("pages_migration_failed")

                res = self.transport.send_paths_chunked(
                    paths, dst.session, bucket=bucket,
                    max_wave_bytes=cfg.migrate_wave_bytes,
                    on_drop=_drop,
                    src=rep.replica_id, dst=dst.replica_id,
                    deadline_s=(cfg.handoff_deadline_ms / 1e3
                                if cfg.handoff_deadline_ms
                                is not None else None),
                    retries=cfg.handoff_retries,
                    backoff_s=cfg.handoff_backoff_ms / 1e3)
                migrated += res["chunks"]
        self._audit_drain(rep)
        del self._replicas[rep.replica_id]
        self.metrics.inc("drains_completed")
        self.metrics.inc("pages_migrated", migrated)
        self._log(self.drain_log, {
            "replica_id": rep.replica_id, "role": rep.role,
            "pages_migrated": migrated,
            "survivors": [r.replica_id for r in survivors],
        })

    def _audit_drain(self, rep: Replica) -> None:
        try:
            from easydist_tpu.analyze import check_fleet_drain

            check_fleet_drain(rep.session,
                              node=f"drain[{rep.replica_id}]")
        except ImportError:
            pass

    # -------------------------------------------------------------- runners
    def run_until_drained(self, max_steps: int = 100000) -> None:
        """Drive `step()` until every submitted request resolved and no
        replica holds live work."""
        for _ in range(max_steps):
            if not self._inflight and not self._handoffs and all(
                    r.session.is_drained for r in self._replicas.values()):
                return
            self.step()
        raise RuntimeError(f"fleet not drained after {max_steps} steps")

    # ------------------------------------------------------------ reporting
    def stats(self) -> Dict[str, object]:
        return {
            "replicas": {
                rid: {"role": r.role,
                      "draining": r.session.is_draining,
                      "queue_depth": r.session.queue_depth,
                      "breaker": (r.breaker.snapshot() if r.breaker
                                  else None)}
                for rid, r in self._replicas.items()},
            "inflight": len(self._inflight),
            "handoffs": len(self._handoffs),
            "decisions": len(self.decision_log),
            "drains": list(self.drain_log),
            "crashes": list(self.crash_log),
            "health": self.health.snapshot(),
            "metrics": self.metrics.snapshot(),
            "protocol_events": len(self.protocol_log),
            "protocol_events_dropped": self.protocol_events_dropped,
        }

    def export_metrics(self, db=None, persist: bool = True):
        """Fleet gauges + every replica's metrics into PerfDB, each under
        its own replica-labeled sub_key (no collisions)."""
        db = self.metrics.export(db=db, key="serving",
                                 sub_key="fleet", persist=False)
        for rep in self._replicas.values():
            rep.session.metrics.export(db=db, persist=False)
        db.append_history("serving", "fleet_routing", {
            "decisions": list(self.decision_log)[-64:],
            "drains": list(self.drain_log),
            "crashes": list(self.crash_log),
            "health_events": list(self.health.events)[-64:],
            "protocol_events": list(self.protocol_log)[-256:],
            "protocol_events_dropped": self.protocol_events_dropped,
        })
        if persist:
            try:
                db.persist()
            except Exception:
                pass
        return db
