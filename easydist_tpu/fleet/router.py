"""`FleetRouter`: N `GenerationSession` replicas behind one submit().

Scales serve/ from one host to a fleet without touching the bitwise
spine: every replica runs the same params and the same compiled programs,
prefix restore is bitwise-equal to recompute, and greedy continuation is
a pure function of the token prefix — so WHICH replica serves a request
(or whether its prefill ran on a different replica, or it migrated
mid-stream during a drain) never changes a single output token.

Routing is a scored policy over live signals:

  * **prefix-cache affinity** — `PrefixCache.peek` (non-mutating) probes
    how many prompt tokens each replica's trie already holds; requests
    sharing a system prefix converge on the replica that warmed it and
    skip that prefill entirely;
  * **occupancy** — free decode-slot fraction from the session's
    `queue_depth`, so affinity never piles everything onto one replica;
  * **breaker/health** — a replica whose `CircuitBreaker` is OPEN is
    ineligible (routing to it anyway is the FLEET001 error);
  * **consistent-hash fallback** — a cold prefix (zero affinity
    everywhere) routes by `HashRing` over its page-aligned prefix, so
    identical cold prefixes co-locate and BUILD affinity instead of
    scattering.

Prefill/decode disaggregation: with dedicated prefill replicas, the
page-aligned prompt prefix runs chunked prefill there, the committed
pages hand off through a `KVTransport` (sha256 page manifest, FLEET002),
and the decode replica restores them on admission — computing only the
unaligned tail.  Elastic drain: `drain(rid)` stops new admits, keeps
stepping the replica until in-flight work retires (other replicas never
stall — zero downtime), migrates its hot trie pages to the survivors,
and audits the emptied trie for orphaned pins (FLEET003).  `evacuate`
mode retires live decodes immediately with partial ids and the router
resubmits prompt+partial elsewhere, bitwise-seamlessly.
"""

from __future__ import annotations

import logging
import random
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from easydist_tpu.resilience.breaker import OPEN, CircuitBreaker
from easydist_tpu.serve.admission import (AdmissionController,
                                          CircuitOpenError,
                                          RequestTooLargeError)
from easydist_tpu.serve.batcher import select_bucket
from easydist_tpu.serve.metrics import ServeMetrics

from .hashring import HashRing, prefix_hash_key
from .transport import InProcessTransport, KVTransport, page_manifest

logger = logging.getLogger(__name__)

__all__ = ["FleetConfig", "FleetRouter", "Replica"]


@dataclass(frozen=True)
class FleetConfig:
    """Routing policy knobs.

    affinity_weight / occupancy_weight: the scored policy is
        w_aff * (cached prefix tokens / prompt tokens)
        + w_occ * (free decode-slot fraction); affinity dominating means
        a warm trie wins unless the replica is nearly full.
    policy: "affinity" (scored + hash fallback) or "random" (uniform —
        the bench's comparison arm, never the production setting).
    vnodes: virtual points per replica on the consistent-hash ring.
    max_queue: fleet-wide bound on live requests; submits beyond it
        raise QueueFullError (the admission layer's check).
    default_deadline_ms: deadline stamped on submits that pass none.
    seed: rng seed for the "random" policy (deterministic benches).
    """
    affinity_weight: float = 2.0
    occupancy_weight: float = 1.0
    policy: str = "affinity"
    vnodes: int = 64
    max_queue: int = 1024
    default_deadline_ms: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        if self.policy not in ("affinity", "random"):
            raise ValueError(f"unknown routing policy {self.policy!r}")
        if self.vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {self.vnodes}")
        if self.affinity_weight < 0 or self.occupancy_weight < 0:
            raise ValueError("routing weights must be >= 0")


@dataclass
class Replica:
    """One registered session + its health surface."""
    replica_id: str
    session: object                      # GenerationSession
    breaker: Optional[CircuitBreaker] = None
    role: str = "decode"                 # "decode" | "prefill"

    def eligible(self) -> bool:
        return (not self.session.is_draining
                and (self.breaker is None or self.breaker.state != OPEN))


@dataclass
class _Inflight:
    """Router-side record of one request across replica hops."""
    request_id: int
    prompt: List[int]
    max_new: int
    eos_id: Optional[int]
    future: Future                       # the caller's future
    acc_ids: List[int] = field(default_factory=list)
    replica_id: Optional[str] = None
    inner: Optional[Future] = None       # current session future
    deadline_t: Optional[float] = None
    t_submit: float = 0.0


@dataclass
class _Handoff:
    """One disaggregated prefill awaiting page transfer."""
    request_id: int
    prefill_replica: str
    decode_replica: str
    aligned: List[int]                   # page-aligned prompt prefix
    inner: Future                        # prefill session future


class FleetRouter:
    """Multi-replica serving front: route, disaggregate, drain."""

    def __init__(self, replicas: Sequence, *,
                 prefill_replicas: Sequence = (),
                 config: Optional[FleetConfig] = None,
                 transport: Optional[KVTransport] = None):
        self.config = config or FleetConfig()
        self.transport = transport or InProcessTransport()
        self._replicas: Dict[str, Replica] = {}
        self._ring = HashRing(vnodes=self.config.vnodes)
        self._prefill_ring = HashRing(vnodes=self.config.vnodes)
        for sess in replicas:
            self.add_replica(sess, role="decode")
        for sess in prefill_replicas:
            self.add_replica(sess, role="prefill")
        if not any(r.role == "decode" for r in self._replicas.values()):
            raise ValueError("fleet needs at least one decode replica")
        self.admission = AdmissionController(
            self.config.max_queue,
            default_deadline_ms=self.config.default_deadline_ms)
        self.metrics = ServeMetrics(replica_id="fleet")
        self._rng = random.Random(self.config.seed)
        self._inflight: Dict[int, _Inflight] = {}
        self._handoffs: List[_Handoff] = []
        self._next_request_id = 0
        # audit surfaces: FLEET001 reads the decision log, FLEET003 the
        # drain log; both bounded so a long-lived router stays O(1)
        self.decision_log: List[Dict[str, object]] = []
        self.drain_log: List[Dict[str, object]] = []
        self._log_cap = 1024

    # ------------------------------------------------------------ replicas
    def add_replica(self, session, role: str = "decode") -> Replica:
        rid = session.replica_id
        if not rid:
            raise ValueError("fleet sessions need a replica_id")
        if rid in self._replicas:
            raise ValueError(f"duplicate replica_id {rid!r}")
        cfg = session.config
        breaker = None
        if cfg.breaker_failure_threshold > 0:
            breaker = CircuitBreaker(
                failure_threshold=cfg.breaker_failure_threshold,
                cooldown_s=cfg.breaker_cooldown_ms / 1e3,
                p99_threshold_s=(cfg.breaker_p99_threshold_ms / 1e3
                                 if cfg.breaker_p99_threshold_ms is not None
                                 else None),
                min_samples=cfg.breaker_min_samples,
                p99=lambda m=session.metrics: m.execute.percentile(99),
                replica_id=rid)
        rep = Replica(replica_id=rid, session=session, breaker=breaker,
                      role=role)
        self._replicas[rid] = rep
        (self._ring if role == "decode" else self._prefill_ring).add(rid)
        return rep

    def replica(self, replica_id: str) -> Replica:
        return self._replicas[replica_id]

    def _decode_replicas(self) -> List[Replica]:
        return [r for r in self._replicas.values() if r.role == "decode"]

    def _prefill_replicas(self) -> List[Replica]:
        return [r for r in self._replicas.values() if r.role == "prefill"]

    # ------------------------------------------------------------- routing
    def _aligned_prefix(self, prompt: Sequence[int]) -> List[int]:
        """Longest trie-page-aligned strict-prefix of `prompt` — the
        affinity/hash identity AND the disaggregated-prefill unit (the
        unaligned tail plus at least one token always prefills on the
        decode replica, matching the trie's max_tokens=len-1 cap)."""
        chunk = None
        for rep in self._decode_replicas():
            chunk = rep.session.bucket_chunk(prompt)
            if chunk:
                break
        if not chunk:
            return list(prompt)
        aligned = ((len(prompt) - 1) // chunk) * chunk
        return list(prompt[:aligned]) if aligned else list(prompt)

    def _route(self, prompt: Sequence[int],
               request_id: int) -> Replica:
        """Pick the decode replica; logs the decision for FLEET001."""
        eligible = [r for r in self._decode_replicas() if r.eligible()]
        if not eligible:
            waits = [r.breaker.retry_after_s()
                     for r in self._decode_replicas() if r.breaker]
            raise CircuitOpenError(
                "no eligible decode replica (all draining or circuit-"
                "open)", retry_after_s=max([0.0] + waits))
        if self.config.policy == "random":
            chosen = self._rng.choice(eligible)
            affinity = 0
        else:
            aff = {r.replica_id: r.session.prefix_affinity(prompt)
                   for r in eligible}
            if max(aff.values()) == 0:
                key = prefix_hash_key(self._aligned_prefix(prompt))
                rid = self._ring.route(
                    key, eligible=[r.replica_id for r in eligible])
                chosen = self._replicas[rid] if rid else eligible[0]
            else:
                def score(r: Replica) -> Tuple[float, int, str]:
                    occ_free = 1.0 - (
                        r.session.queue_depth
                        / max(1, r.session.config.max_decode_slots))
                    s = (self.config.affinity_weight
                         * aff[r.replica_id] / len(prompt)
                         + self.config.occupancy_weight
                         * max(0.0, occ_free))
                    # deterministic tie-break: least loaded, then id
                    return (-s, r.session.queue_depth, r.replica_id)
                chosen = min(eligible, key=score)
            affinity = aff.get(chosen.replica_id, 0) \
                if self.config.policy == "affinity" else 0
        self._log(self.decision_log, {
            "request_id": request_id,
            "replica_id": chosen.replica_id,
            "breaker_state": (chosen.breaker.state if chosen.breaker
                              else "closed"),
            "draining": chosen.session.is_draining,
            "affinity_tokens": affinity,
            "prompt_tokens": len(prompt),
            "policy": self.config.policy,
        })
        if affinity:
            self.metrics.inc("routed_warm")
        self.metrics.inc("routed")
        return chosen

    def _log(self, log: List, entry: Dict) -> None:
        log.append(entry)
        del log[:-self._log_cap]

    # ------------------------------------------------------------ admission
    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 16,
               eos_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Route one prompt into the fleet; the returned future resolves
        to the same {"ids", "finish_reason"} a single session produces,
        plus "replica_id" (the LAST replica that decoded it)."""
        prompt = [int(t) for t in prompt_ids]
        any_fit = any(
            select_bucket(len(prompt) + 1,
                          r.session.config.decode_buckets) is not None
            for r in self._decode_replicas())
        if prompt and not any_fit:
            raise RequestTooLargeError(
                f"prompt of {len(prompt)} tokens fits no replica's decode "
                f"buckets")
        self.admission.check_depth(self.total_queue_depth)
        deadline_t = self.admission.resolve_deadline(deadline_ms)
        rid = self._next_request_id
        self._next_request_id += 1
        rec = _Inflight(request_id=rid, prompt=prompt,
                        max_new=max_new_tokens, eos_id=eos_id,
                        future=Future(), deadline_t=deadline_t,
                        t_submit=time.perf_counter())
        chosen = self._route(prompt, rid)
        self._inflight[rid] = rec
        if not self._start_disaggregated(rec, chosen):
            rec.replica_id = chosen.replica_id
            rec.inner = chosen.session.submit(
                prompt, max_new_tokens=max_new_tokens, eos_id=eos_id)
        self.metrics.inc("requests_submitted")
        self.metrics.set_gauge("queue_depth", self.total_queue_depth)
        return rec.future

    def _start_disaggregated(self, rec: _Inflight,
                             decode_rep: Replica) -> bool:
        """Run the page-aligned prefix on a dedicated prefill replica when
        that saves decode-side prefill; returns False to submit directly
        (no prefill tier, prompt under one page, decode trie already
        warm, or page sizes disagree across tiers)."""
        prefill = [r for r in self._prefill_replicas() if r.eligible()]
        if not prefill:
            return False
        aligned = self._aligned_prefix(rec.prompt)
        chunk = decode_rep.session.bucket_chunk(rec.prompt)
        if not chunk or len(aligned) < chunk \
                or len(aligned) == len(rec.prompt):
            return False
        if decode_rep.session.prefix_affinity(rec.prompt) >= len(aligned):
            return False  # decode trie already holds everything aligned
        src = prefill[0]
        if len(prefill) > 1:
            rid = self._prefill_ring.route(
                prefix_hash_key(aligned),
                eligible=[r.replica_id for r in prefill])
            src = self._replicas[rid] if rid else prefill[0]
        if src.session.bucket_chunk(aligned) != chunk:
            return False  # page sizes disagree; handoff would be refused
        rec.replica_id = decode_rep.replica_id
        inner = src.session.submit(aligned, max_new_tokens=1)
        self._handoffs.append(_Handoff(
            request_id=rec.request_id, prefill_replica=src.replica_id,
            decode_replica=decode_rep.replica_id, aligned=aligned,
            inner=inner))
        self.metrics.inc("prefill_handoffs")
        return True

    @property
    def total_queue_depth(self) -> int:
        return sum(r.session.queue_depth
                   for r in self._replicas.values()) + len(self._handoffs)

    # -------------------------------------------------------------- driving
    def step(self) -> int:
        """One fleet round: step EVERY replica (draining ones included —
        their in-flight work retires while the others keep serving; that
        is the zero-downtime property), then harvest handoffs, replica
        hops, completions, and finished drains.  Returns decode tokens
        generated across the fleet this round."""
        tokens = 0
        for rep in self._replicas.values():
            sess = rep.session
            had_work = not sess.is_drained
            try:
                tokens += sess.step()
            except Exception:
                if rep.breaker is not None:
                    rep.breaker.record_failure()
                raise
            if had_work and rep.breaker is not None:
                rep.breaker.record_success()
        self._poll_handoffs()
        self._poll_inflight()
        self._poll_drains()
        self.metrics.set_gauge("queue_depth", self.total_queue_depth)
        return tokens

    def _poll_handoffs(self) -> None:
        for h in list(self._handoffs):
            if not h.inner.done():
                continue
            self._handoffs.remove(h)
            rec = self._inflight.get(h.request_id)
            if rec is None:
                continue
            result = h.inner.result()
            dst = self._replicas[h.decode_replica]
            if result["finish_reason"] != "length":
                # prefill replica was evacuated under us: nothing
                # committed for sure — decode replica prefills from zero
                logger.warning("prefill handoff %s interrupted (%s); "
                               "falling back to direct prefill",
                               h.request_id, result["finish_reason"])
            else:
                src = self._replicas[h.prefill_replica]
                path = src.session.export_prefix_path(h.aligned)
                moved = self.transport.transfer(
                    path, dst.session, rec.prompt,
                    src=h.prefill_replica, dst=h.decode_replica)
                self.metrics.inc("pages_handed_off", moved)
            if not dst.eligible():
                # decode target started draining while prefill ran:
                # re-route; restore == recompute keeps parity either way
                try:
                    dst = self._route(rec.prompt, rec.request_id)
                except CircuitOpenError as e:
                    del self._inflight[rec.request_id]
                    rec.future.set_exception(e)
                    self.metrics.inc("requests_failed")
                    continue
            rec.replica_id = dst.replica_id
            rec.inner = dst.session.submit(
                rec.prompt, max_new_tokens=rec.max_new,
                eos_id=rec.eos_id)

    def _poll_inflight(self) -> None:
        for rid, rec in list(self._inflight.items()):
            if rec.inner is None or not rec.inner.done():
                continue
            result = rec.inner.result()
            if result["finish_reason"] == "evacuated":
                # mid-stream migration: greedy continuation is a pure
                # function of the prefix, so prompt+partial resumed on
                # any replica concatenates bitwise-identically
                rec.acc_ids.extend(result["ids"])
                remaining = rec.max_new - len(rec.acc_ids)
                try:
                    nxt = self._route(rec.prompt + rec.acc_ids,
                                      rec.request_id)
                except CircuitOpenError as e:
                    del self._inflight[rid]
                    rec.future.set_exception(e)
                    self.metrics.inc("requests_failed")
                    continue
                rec.replica_id = nxt.replica_id
                rec.inner = nxt.session.submit(
                    rec.prompt + rec.acc_ids, max_new_tokens=remaining,
                    eos_id=rec.eos_id)
                self.metrics.inc("migrations")
                continue
            del self._inflight[rid]
            rec.future.set_result({
                "ids": rec.acc_ids + result["ids"],
                "finish_reason": result["finish_reason"],
                "replica_id": rec.replica_id,
            })
            self.metrics.inc("requests_completed")
            self.metrics.observe(
                "e2e", time.perf_counter() - rec.t_submit)

    def _poll_drains(self) -> None:
        for rep in list(self._replicas.values()):
            if not rep.session.is_draining or not rep.session.is_drained:
                continue
            if any(h.prefill_replica == rep.replica_id
                   or h.decode_replica == rep.replica_id
                   for h in self._handoffs):
                continue  # let pending handoffs clear first
            self._finish_drain(rep)

    # --------------------------------------------------------------- drain
    def drain(self, replica_id: str, mode: str = "graceful") -> None:
        """Begin removing one replica with zero dropped requests.

        "graceful": stop new admits, keep stepping until its in-flight
        decodes retire naturally.  "evacuate": retire live work NOW with
        partial ids (SIGTERM-grace semantics — resilience/preempt.py);
        the inflight poller resubmits each prompt+partial elsewhere.
        Either way the replica's hot trie pages migrate to the survivors
        before it is removed (next step() after it empties)."""
        rep = self._replicas[replica_id]
        (self._ring if rep.role == "decode"
         else self._prefill_ring).remove(replica_id)
        if mode == "evacuate":
            rep.session.evacuate()
        elif mode == "graceful":
            rep.session.drain(wait=False)
        else:
            raise ValueError(f"unknown drain mode {mode!r}")
        self.metrics.inc("drains_started")

    def _finish_drain(self, rep: Replica) -> None:
        pages = rep.session.export_hot_pages()
        survivors = [r for r in self._decode_replicas()
                     if r.replica_id != rep.replica_id and r.eligible()]
        migrated = 0
        for bucket, paths in pages.items():
            for path in paths:
                # manifest-verified like any other handoff (FLEET002)
                manifest = page_manifest(path, src=rep.replica_id,
                                         dst="survivors")
                self._check_handoff(manifest, path, rep.replica_id)
                for dst in survivors:
                    migrated += dst.session.import_hot_pages(
                        {bucket: [path]})
        self._audit_drain(rep)
        del self._replicas[rep.replica_id]
        self.metrics.inc("drains_completed")
        self.metrics.inc("pages_migrated", migrated)
        self._log(self.drain_log, {
            "replica_id": rep.replica_id, "role": rep.role,
            "pages_migrated": migrated,
            "survivors": [r.replica_id for r in survivors],
        })

    def _check_handoff(self, manifest, path, src: str) -> None:
        try:
            from easydist_tpu.analyze import check_page_handoff

            check_page_handoff(manifest, path,
                               node=f"drain[{src}]")
        except ImportError:
            pass

    def _audit_drain(self, rep: Replica) -> None:
        try:
            from easydist_tpu.analyze import check_fleet_drain

            check_fleet_drain(rep.session,
                              node=f"drain[{rep.replica_id}]")
        except ImportError:
            pass

    # -------------------------------------------------------------- runners
    def run_until_drained(self, max_steps: int = 100000) -> None:
        """Drive `step()` until every submitted request resolved and no
        replica holds live work."""
        for _ in range(max_steps):
            if not self._inflight and not self._handoffs and all(
                    r.session.is_drained for r in self._replicas.values()):
                return
            self.step()
        raise RuntimeError(f"fleet not drained after {max_steps} steps")

    # ------------------------------------------------------------ reporting
    def stats(self) -> Dict[str, object]:
        return {
            "replicas": {
                rid: {"role": r.role,
                      "draining": r.session.is_draining,
                      "queue_depth": r.session.queue_depth,
                      "breaker": (r.breaker.snapshot() if r.breaker
                                  else None)}
                for rid, r in self._replicas.items()},
            "inflight": len(self._inflight),
            "handoffs": len(self._handoffs),
            "decisions": len(self.decision_log),
            "drains": list(self.drain_log),
            "metrics": self.metrics.snapshot(),
        }

    def export_metrics(self, db=None, persist: bool = True):
        """Fleet gauges + every replica's metrics into PerfDB, each under
        its own replica-labeled sub_key (no collisions)."""
        db = self.metrics.export(db=db, key="serving",
                                 sub_key="fleet", persist=False)
        for rep in self._replicas.values():
            rep.session.metrics.export(db=db, persist=False)
        db.append_history("serving", "fleet_routing", {
            "decisions": list(self.decision_log)[-64:],
            "drains": list(self.drain_log),
        })
        if persist:
            try:
                db.persist()
            except Exception:
                pass
        return db
