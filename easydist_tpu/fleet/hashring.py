"""Consistent-hash ring for cold-prefix placement.

A prompt whose prefix no replica's trie holds yet has no affinity signal;
routing it uniformly at random would scatter identical system prefixes
across the fleet and every replica would pay the same prefill.  Hashing
the trie-page-aligned prefix onto a ring instead makes COLD placement
sticky: the second request sharing the prefix lands on the same replica
the first one warmed, and from then on affinity scoring takes over.

Classic ring: each replica owns `vnodes` points (sha256 of
"replica_id#i"), a key routes to the first point clockwise from its own
hash, and adding/removing one replica only remaps the ~1/N of keyspace
adjacent to its points — a drain does not reshuffle every other replica's
warm prefixes.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import List, Optional, Sequence, Tuple

__all__ = ["HashRing", "prefix_hash_key"]


def _point(label: str) -> int:
    return int.from_bytes(
        hashlib.sha256(label.encode("utf-8")).digest()[:8], "big")


def prefix_hash_key(tokens: Sequence[int]) -> int:
    """Ring position of a token prefix (exact over the ids — two prompts
    share a key iff they share the whole aligned prefix)."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(8, "big", signed=True))
    return int.from_bytes(h.digest()[:8], "big")


class HashRing:
    """Sorted virtual-point ring over replica ids."""

    def __init__(self, replica_ids: Sequence[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._points: List[Tuple[int, str]] = []
        for rid in replica_ids:
            self.add(rid)

    def add(self, replica_id: str) -> None:
        for i in range(self.vnodes):
            bisect.insort(self._points,
                          (_point(f"{replica_id}#{i}"), replica_id))

    def remove(self, replica_id: str) -> None:
        self._points = [(p, r) for p, r in self._points if r != replica_id]

    def replicas(self) -> List[str]:
        return sorted({r for _, r in self._points})

    def route(self, key: int,
              eligible: Optional[Sequence[str]] = None) -> Optional[str]:
        """First eligible replica clockwise from `key`; None when the
        ring is empty or nothing eligible remains."""
        if not self._points:
            return None
        allowed = None if eligible is None else set(eligible)
        start = bisect.bisect_left(self._points, (key, ""))
        n = len(self._points)
        seen = set()
        for off in range(n):
            point, rid = self._points[(start + off) % n]
            if rid in seen:
                continue
            seen.add(rid)
            if allowed is None or rid in allowed:
                return rid
        return None
