"""KV-page transport for prefill/decode disaggregation and drain
migration.

The unit of transfer is the prefix trie's committed page: one aligned
`prefill_chunk`-token window of K/V, shape [layers, (kv_)heads, chunk,
head_dim] per array (serve/prefix_cache.py).  A transfer ships a
root-to-leaf chunk *path* — pages are only meaningful with every ancestor
present (causal attention: a page's K/V depends on all tokens before it).

Every transfer carries a **manifest** in the checkpoint MANIFEST.json
idiom (runtime/checkpoint.py): per-page sha256 over the token ids and the
raw K/V bytes, so the receiver verifies integrity before committing —
FLEET002 makes a digest mismatch an error finding, because a corrupt page
restored into a live trie poisons every future request sharing that
prefix, bitwise-silently.

`InProcessTransport` moves device arrays by reference (same process, same
backend) and still builds + verifies the manifest — the serialized format
is the contract a DCN transport implements later; the in-process one
proves it round-trips.

Hardening (`send_pages`): a single `transfer()` is one verify-then-commit
attempt; `send_pages` wraps it with a deadline and jittered exponential
backoff (the checkpoint layer's `_retry_io` idiom), classifies only
transport faults as retryable (`TransportStallError` — the attempt hung;
`PageCorruptError` — the payload failed manifest verification BEFORE any
commit), and relies on idempotent manifest-keyed commits so an attempt
retried after a late/duplicated delivery never double-commits.  The
abort-on-partial property is structural: verification covers page count
and every digest, and runs before the first page touches the trie — a
half-arrived prefix can never enter it.  Fault points
`fleet.transport.stall` / `fleet.transport.page_corrupt` inject both
failure shapes deterministically (the corrupt attempt flips a bit in a
COPY of one in-flight page, so the retry resends pristine bytes).
"""

from __future__ import annotations

import copy
import hashlib
import logging
import random
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from easydist_tpu.resilience import faultinject

logger = logging.getLogger(__name__)

MANIFEST_FORMAT = 1

Page = Tuple[Tuple[int, ...], Dict[str, object]]  # (chunk_tokens, {"k","v"})


class TransportError(RuntimeError):
    """Base for transfer failures a router can act on (retry/fallback)."""


class TransportStallError(TransportError):
    """One transfer attempt hung past its budget — transient, retryable."""


class PageCorruptError(TransportError):
    """Manifest verification failed: the payload was damaged in flight.
    Raised BEFORE anything commits (abort-on-partial); retryable because
    the source still holds pristine pages."""


def path_nbytes(path: Sequence[Page]) -> int:
    """Payload bytes of one chunk path (K/V arrays only; token ids are
    noise at page granularity).  `.nbytes` is metadata on both numpy and
    device arrays — sizing a path never forces a host copy."""
    total = 0
    for _tokens, kv in path:
        for arr in kv.values():
            nb = getattr(arr, "nbytes", None)
            total += int(nb if nb is not None else np.asarray(arr).nbytes)
    return total


def manifest_key(manifest: Dict[str, object]) -> str:
    """Stable identity of one transfer's CONTENT: sha256 over the ordered
    page digests.  Two attempts shipping the same pages share a key, so
    the receiver can make commits idempotent under retry."""
    h = hashlib.sha256()
    for entry in manifest.get("pages", []):
        h.update(str(entry.get("sha256")).encode())
    return h.hexdigest()


def _corrupt_in_flight(path: Sequence[Page]) -> List[Page]:
    """Deep-copy the path and flip one value in the last page's first
    array — the deterministic stand-in for damage on the wire.  The
    caller's arrays are untouched (a retry resends pristine bytes)."""
    damaged = [(tokens, {k: copy.deepcopy(np.asarray(v))
                         for k, v in kv.items()})
               for tokens, kv in path]
    tokens, kv = damaged[-1]
    arr = kv[sorted(kv)[0]]
    arr.flat[0] += 1 if arr.dtype.kind in "iu" else 1e-3
    return damaged


def _page_digest(tokens: Sequence[int], kv: Dict[str, object]) -> Tuple[str, int]:
    """sha256 over the page identity AND payload: token ids, then each
    array's dtype/shape/raw bytes in key order — any bit flip anywhere in
    the page changes the digest.  Key order covers every arena leaf, so
    quantized pages' scale planes (`k_scale`/`v_scale`) enter the digest
    alongside the int8 payloads — a scale/payload desync across the wire
    fails verification exactly like a flipped payload bit."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(8, "big", signed=True))
    nbytes = 0
    for name in sorted(kv):
        arr = np.asarray(kv[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        buf = np.ascontiguousarray(arr).tobytes()
        h.update(buf)
        nbytes += len(buf)
    return h.hexdigest(), nbytes


def page_manifest(path: Sequence[Page], src: str = "?",
                  dst: str = "?") -> Dict[str, object]:
    """Serializable description of one chunk-path transfer (JSON-safe:
    token ids + digests, never array payloads)."""
    pages = []
    for idx, (tokens, kv) in enumerate(path):
        digest, nbytes = _page_digest(tokens, kv)
        pages.append({"index": idx, "tokens": [int(t) for t in tokens],
                      "sha256": digest, "bytes": nbytes})
    return {"format": MANIFEST_FORMAT, "src": src, "dst": dst,
            "pages": pages}


def verify_manifest(manifest: Dict[str, object],
                    path: Sequence[Page]) -> List[str]:
    """Recompute every page digest against the manifest; returns problem
    strings (empty = intact).  The FLEET002 audit wraps this."""
    problems: List[str] = []
    entries = manifest.get("pages", [])
    if manifest.get("format") != MANIFEST_FORMAT:
        problems.append(f"manifest format {manifest.get('format')!r} != "
                        f"{MANIFEST_FORMAT}")
    if len(entries) != len(path):
        problems.append(f"manifest lists {len(entries)} pages, transfer "
                        f"carries {len(path)}")
    for entry, (tokens, kv) in zip(entries, path):
        want_tokens = [int(t) for t in entry.get("tokens", [])]
        if want_tokens != [int(t) for t in tokens]:
            problems.append(f"page {entry.get('index')}: token ids differ "
                            f"from manifest")
            continue
        digest, nbytes = _page_digest(tokens, kv)
        if digest != entry.get("sha256"):
            problems.append(
                f"page {entry.get('index')}: sha256 mismatch (manifest "
                f"{str(entry.get('sha256'))[:12]}.., payload "
                f"{digest[:12]}..)")
        elif nbytes != entry.get("bytes"):
            problems.append(f"page {entry.get('index')}: {nbytes} payload "
                            f"bytes != manifest {entry.get('bytes')}")
    return problems


class KVTransport:
    """Moves one committed chunk path between replicas.  Implementations
    must build a manifest at the source and verify it at the destination
    before committing anything, and keep commits idempotent under the
    manifest key (send_pages retries on transient failures)."""

    def transfer(self, path: Sequence[Page], dst_session, prompt,
                 src: str = "?", dst: str = "?",
                 bucket: Optional[int] = None) -> int:
        raise NotImplementedError

    def send_pages(self, path: Sequence[Page], dst_session, prompt=None,
                   *, bucket: Optional[int] = None,
                   src: str = "?", dst: str = "?",
                   deadline_s: Optional[float] = None, retries: int = 2,
                   backoff_s: float = 0.005, jitter: float = 0.25,
                   clock=time.monotonic, sleep=time.sleep,
                   rng=random.random) -> int:
        """`transfer` with a deadline and jittered-backoff retry (the
        checkpoint `_retry_io` idiom).  Only transport faults retry —
        a stalled attempt (`TransportStallError`, injectable via
        `fleet.transport.stall`) or a payload that failed verification
        (`PageCorruptError`); logic errors propagate immediately, and a
        retry that would start past the deadline raises the last error
        instead of sleeping through it.  Commit with `prompt` (trie path
        for that prompt's bucket) or `bucket` (drain-migration hot
        pages)."""
        deadline_t = None if deadline_s is None else clock() + deadline_s
        attempt = 0
        while True:
            try:
                if faultinject.fire("fleet.transport.stall"):
                    raise TransportStallError(
                        f"injected transfer stall ({src}->{dst}, "
                        f"attempt {attempt + 1})")
                return self.transfer(path, dst_session, prompt,
                                     src=src, dst=dst, bucket=bucket)
            except (TransportStallError, PageCorruptError) as e:
                attempt += 1
                if attempt > retries:
                    raise
                delay = backoff_s * (2 ** (attempt - 1)) \
                    * (1.0 + jitter * rng())
                if deadline_t is not None and clock() + delay >= deadline_t:
                    logger.warning(
                        "page transfer %s->%s: deadline exhausted after "
                        "%d attempt(s): %s", src, dst, attempt, e)
                    raise
                logger.warning(
                    "page transfer %s->%s attempt %d failed (%s); "
                    "retrying in %.1fms", src, dst, attempt, e,
                    delay * 1e3)
                sleep(delay)

    def send_paths_chunked(self, paths: Sequence[Sequence[Page]],
                           dst_session, *, bucket: Optional[int] = None,
                           max_wave_bytes: Optional[int] = None,
                           on_drop=None, src: str = "?", dst: str = "?",
                           **send_kw) -> Dict[str, int]:
        """Migrate many chunk paths in byte-bounded WAVES.

        Drain migration ships a draining replica's whole hot working set;
        unbatched, its in-flight bytes scale with trie warmth.  This
        reuses the reshard planner's wave batcher (`reshard.chunk_waves`
        — the same greedy prefix grouping that bounds redistribution
        chunks) to cap the bytes entering `send_pages` per wave at
        `max_wave_bytes` (falls back to `edconfig.reshard_chunk_bytes`;
        a single path over the cap ships alone — paths are indivisible,
        ancestors must land with descendants).

        Per-path semantics are unchanged: each path still goes through
        `send_pages` (manifest verify, retry, idempotent commit), and a
        path that fails permanently is reported via `on_drop(i, error)`
        and skipped — best-effort drain, never half-committed.  Returns
        {"chunks", "paths_sent", "paths_dropped", "waves", "bytes"}.
        """
        from easydist_tpu.reshard import chunk_waves

        paths = list(paths)
        if max_wave_bytes is None:
            from easydist_tpu import config as edconfig

            max_wave_bytes = edconfig.reshard_chunk_bytes
        sizes = [path_nbytes(p) for p in paths]
        out = {"chunks": 0, "paths_sent": 0, "paths_dropped": 0,
               "waves": 0, "bytes": 0}
        for lo, hi in chunk_waves(sizes, max_wave_bytes):
            out["waves"] += 1
            for i in range(lo, hi):
                try:
                    out["chunks"] += self.send_pages(
                        paths[i], dst_session, None, bucket=bucket,
                        src=src, dst=dst, **send_kw)
                    out["paths_sent"] += 1
                    out["bytes"] += sizes[i]
                except TransportError as e:
                    out["paths_dropped"] += 1
                    if on_drop is not None:
                        on_drop(i, e)
                    else:
                        logger.warning(
                            "chunked migration %s->%s dropped path %d: "
                            "%s", src, dst, i, e)
        return out


class InProcessTransport(KVTransport):
    """Same-process transfer: pages move by reference, the manifest still
    round-trips (and is kept in `manifests` for audit/tests).  Commits
    are idempotent per (destination, commit target, manifest key): a
    retried/duplicated delivery of the same pages returns the first
    commit's result without touching the trie again."""

    def __init__(self, verify: bool = True, keep: int = 32,
                 keep_commits: int = 256):
        self.verify = verify
        self.keep = keep
        self.keep_commits = keep_commits
        self.manifests: List[Dict[str, object]] = []
        self.pages_moved = 0
        self.commits_deduped = 0
        self._committed: Dict[tuple, int] = {}
        # layer-12 conformance surface: committed/deduped/rejected per
        # manifest key, replayed through the TransportSpec's idempotence
        # relation by `replay_transport_commits` (PROTO003)
        self.events: List[Dict[str, object]] = []
        self._event_cap = 512

    def _event(self, event: str, key: tuple, src: str, dst: str) -> None:
        self.events.append({"event": event, "key": key[1:],
                            "src": src, "dst": dst})
        del self.events[:-self._event_cap]

    def transitions(self) -> List[Dict[str, object]]:
        """The commit event stream, oldest first — the surface
        `replay_transport_commits` (PROTO003) validates against the
        TransportSpec."""
        return list(self.events)

    def transfer(self, path: Sequence[Page], dst_session, prompt,
                 src: str = "?", dst: str = "?",
                 bucket: Optional[int] = None) -> int:
        """One verify-then-commit attempt: commit `path` into
        `dst_session`'s trie for `prompt`'s decode bucket (or as hot
        pages under `bucket` when prompt is None); returns chunks present
        after import.  Verification failure raises `PageCorruptError`
        BEFORE anything commits.

        The idempotence lookup runs FIRST: a duplicate delivery of
        already-committed content (the manifest key is computed over the
        pristine source pages) is a pure no-op — it must not re-append
        to the audit trail, burn a fault-plan occurrence, or re-run
        verification (a duplicate damaged in flight after a successful
        commit would otherwise turn a no-op into a spurious
        PageCorruptError).  This is the commit-boundary idempotence the
        TransportSpec model-checks."""
        if not path:
            return 0
        manifest = page_manifest(path, src=src, dst=dst)
        target = (tuple(int(t) for t in prompt) if prompt is not None
                  else ("bucket", bucket))
        key = (id(dst_session), target, manifest_key(manifest))
        if key in self._committed:
            self.commits_deduped += 1
            self._event("deduped", key, src, dst)
            return self._committed[key]
        self.manifests = (self.manifests + [manifest])[-self.keep:]
        if faultinject.fire("fleet.transport.page_corrupt"):
            # damage on the wire: manifest was built over pristine pages,
            # the payload mutates after — verification must catch it
            path = _corrupt_in_flight(path)
        if self.verify:
            try:
                self._check(manifest, path)
            except Exception as e:
                self._event("rejected", key, src, dst)
                raise PageCorruptError(
                    f"KV page handoff corrupt; aborted before commit "
                    f"({src}->{dst}): {e}") from e
        if prompt is not None:
            n = dst_session.import_prefix_path(prompt, path)
        else:
            n = dst_session.import_hot_pages({bucket: [path]})
        self.pages_moved += len(path)
        self._committed[key] = n
        self._event("committed", key, src, dst)
        while len(self._committed) > self.keep_commits:
            self._committed.pop(next(iter(self._committed)))
        return n

    def _check(self, manifest, path) -> None:
        try:
            from easydist_tpu.analyze import check_page_handoff

            # FLEET002 audit trail; raises AnalysisError under
            # edconfig.analyze_raise
            check_page_handoff(manifest, path,
                               node=f"handoff[{manifest['src']}->"
                                    f"{manifest['dst']}]")
        except ImportError:  # analyze is an optional layer at runtime
            pass
        # commits must abort on damage even with analyze_raise off
        problems = verify_manifest(manifest, path)
        if problems:
            raise RuntimeError(f"KV page handoff corrupt: {problems}")
