"""KV-page transport for prefill/decode disaggregation and drain
migration.

The unit of transfer is the prefix trie's committed page: one aligned
`prefill_chunk`-token window of K/V, shape [layers, (kv_)heads, chunk,
head_dim] per array (serve/prefix_cache.py).  A transfer ships a
root-to-leaf chunk *path* — pages are only meaningful with every ancestor
present (causal attention: a page's K/V depends on all tokens before it).

Every transfer carries a **manifest** in the checkpoint MANIFEST.json
idiom (runtime/checkpoint.py): per-page sha256 over the token ids and the
raw K/V bytes, so the receiver verifies integrity before committing —
FLEET002 makes a digest mismatch an error finding, because a corrupt page
restored into a live trie poisons every future request sharing that
prefix, bitwise-silently.

`InProcessTransport` moves device arrays by reference (same process, same
backend) and still builds + verifies the manifest — the serialized format
is the contract a DCN transport implements later; the in-process one
proves it round-trips.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MANIFEST_FORMAT = 1

Page = Tuple[Tuple[int, ...], Dict[str, object]]  # (chunk_tokens, {"k","v"})


def _page_digest(tokens: Sequence[int], kv: Dict[str, object]) -> Tuple[str, int]:
    """sha256 over the page identity AND payload: token ids, then each
    array's dtype/shape/raw bytes in key order — any bit flip anywhere in
    the page changes the digest."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(8, "big", signed=True))
    nbytes = 0
    for name in sorted(kv):
        arr = np.asarray(kv[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        buf = np.ascontiguousarray(arr).tobytes()
        h.update(buf)
        nbytes += len(buf)
    return h.hexdigest(), nbytes


def page_manifest(path: Sequence[Page], src: str = "?",
                  dst: str = "?") -> Dict[str, object]:
    """Serializable description of one chunk-path transfer (JSON-safe:
    token ids + digests, never array payloads)."""
    pages = []
    for idx, (tokens, kv) in enumerate(path):
        digest, nbytes = _page_digest(tokens, kv)
        pages.append({"index": idx, "tokens": [int(t) for t in tokens],
                      "sha256": digest, "bytes": nbytes})
    return {"format": MANIFEST_FORMAT, "src": src, "dst": dst,
            "pages": pages}


def verify_manifest(manifest: Dict[str, object],
                    path: Sequence[Page]) -> List[str]:
    """Recompute every page digest against the manifest; returns problem
    strings (empty = intact).  The FLEET002 audit wraps this."""
    problems: List[str] = []
    entries = manifest.get("pages", [])
    if manifest.get("format") != MANIFEST_FORMAT:
        problems.append(f"manifest format {manifest.get('format')!r} != "
                        f"{MANIFEST_FORMAT}")
    if len(entries) != len(path):
        problems.append(f"manifest lists {len(entries)} pages, transfer "
                        f"carries {len(path)}")
    for entry, (tokens, kv) in zip(entries, path):
        want_tokens = [int(t) for t in entry.get("tokens", [])]
        if want_tokens != [int(t) for t in tokens]:
            problems.append(f"page {entry.get('index')}: token ids differ "
                            f"from manifest")
            continue
        digest, nbytes = _page_digest(tokens, kv)
        if digest != entry.get("sha256"):
            problems.append(
                f"page {entry.get('index')}: sha256 mismatch (manifest "
                f"{str(entry.get('sha256'))[:12]}.., payload "
                f"{digest[:12]}..)")
        elif nbytes != entry.get("bytes"):
            problems.append(f"page {entry.get('index')}: {nbytes} payload "
                            f"bytes != manifest {entry.get('bytes')}")
    return problems


class KVTransport:
    """Moves one committed chunk path between replicas.  Implementations
    must build a manifest at the source and verify it at the destination
    before committing anything."""

    def transfer(self, path: Sequence[Page], dst_session, prompt,
                 src: str = "?", dst: str = "?") -> int:
        raise NotImplementedError


class InProcessTransport(KVTransport):
    """Same-process transfer: pages move by reference, the manifest still
    round-trips (and is kept in `manifests` for audit/tests)."""

    def __init__(self, verify: bool = True, keep: int = 32):
        self.verify = verify
        self.keep = keep
        self.manifests: List[Dict[str, object]] = []
        self.pages_moved = 0

    def transfer(self, path: Sequence[Page], dst_session, prompt,
                 src: str = "?", dst: str = "?") -> int:
        """Verify + commit `path` into `dst_session`'s trie for `prompt`'s
        decode bucket; returns chunks present after import."""
        if not path:
            return 0
        manifest = page_manifest(path, src=src, dst=dst)
        self.manifests = (self.manifests + [manifest])[-self.keep:]
        if self.verify:
            self._check(manifest, path)
        n = dst_session.import_prefix_path(prompt, path)
        self.pages_moved += len(path)
        return n

    def _check(self, manifest, path) -> None:
        try:
            from easydist_tpu.analyze import check_page_handoff
        except ImportError:  # analyze is an optional layer at runtime
            problems = verify_manifest(manifest, path)
            if problems:
                raise RuntimeError(
                    f"KV page handoff corrupt: {problems}")
            return
        check_page_handoff(manifest, path,
                           node=f"handoff[{manifest['src']}->"
                                f"{manifest['dst']}]")
