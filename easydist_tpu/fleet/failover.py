"""Bitwise in-flight recovery: resume descriptors + poison quarantine.

The recovery contract rides the bitwise spine: greedy continuation is a
pure function of the token prefix, so a request that lost its replica
mid-stream resumes EXACTLY by resubmitting ``prompt + already-emitted
ids`` with the remaining budget to any surviving replica — the
concatenated stream is token-for-token identical to an uninterrupted
run.  This generalizes the evacuate path (serve/generation.py
`evacuate()` returns the same shape cooperatively); the crash path
cannot ask the dead session anything, so the ROUTER keeps each request's
`ResumeDescriptor` current by syncing emitted tokens from the live
session after every successful step — mirroring what a streaming client
would already have received when the replica died.

Quarantine: a request that has now crashed `quarantine_after` DISTINCT
replicas is overwhelmingly likely to be the *cause* (a poison request —
some input that deterministically kills whatever serves it).  Rolling it
through the fleet would take every replica down in sequence; instead its
future fails with the structured `PoisonRequestError` naming the
replicas it took down, and the fleet keeps serving everyone else.

FLEET005 (analyze layer 6) audits every resume before it is submitted:
the resubmitted prompt must be exactly original-prompt + emitted-ids,
the budget must have room left, and the emitted ids must not already
contain eos — any mismatch means the recovery would SILENTLY change
tokens, which is the one thing this layer exists to prevent.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Set

from easydist_tpu.serve.admission import ServeError

__all__ = ["ResumeDescriptor", "PoisonRequestError"]


class PoisonRequestError(ServeError):
    """Request rejected after crashing `quarantine_after` distinct
    replicas; carries the evidence a client/operator needs."""

    def __init__(self, request_id: int, replicas: Set[str]):
        self.request_id = request_id
        self.replicas = set(replicas)
        super().__init__(
            f"request {request_id} quarantined: crashed "
            f"{len(self.replicas)} distinct replica(s) "
            f"({sorted(self.replicas)}); refusing further resubmission")


@dataclass
class ResumeDescriptor:
    """Everything needed to continue one request on another replica.

    `ids` is the stream already emitted to the caller (synced from the
    serving session after each successful step, or harvested from a
    cooperative evacuate); `resume_prompt()` is the exact token prefix a
    surviving replica continues from, and `remaining()` the budget left.
    `crashed_on` accumulates replica ids this request was on when they
    died — the quarantine signal."""
    request_id: int
    prompt: List[int]
    max_new: int
    eos_id: Optional[int]
    ids: List[int] = field(default_factory=list)
    crashed_on: Set[str] = field(default_factory=set)

    def resume_prompt(self) -> List[int]:
        return list(self.prompt) + list(self.ids)

    def remaining(self) -> int:
        return self.max_new - len(self.ids)

    def finished(self) -> bool:
        """Nothing left to resume: budget exhausted or eos emitted."""
        return self.remaining() <= 0 or (
            self.eos_id is not None and self.eos_id in self.ids)

    def as_dict(self) -> dict:
        return {"request_id": self.request_id,
                "prompt": list(self.prompt), "ids": list(self.ids),
                "max_new": self.max_new, "eos_id": self.eos_id,
                "crashed_on": sorted(self.crashed_on)}
