"""Replica failure detection: step-liveness heartbeats + stall probes.

A crashed replica is easy — its `step()` raises and the router fails over
on the spot.  The dangerous failure is the WEDGED replica: the process is
alive, `step()` returns, but no work retires (device hang, deadlocked
host thread, runaway collective).  Requests parked there starve silently
while the breaker — which only counts *exceptions* — stays CLOSED.

`HealthMonitor` closes that gap with a liveness heartbeat derived from
the counters every session already keeps (`ServeMetrics.decode_steps`,
`prefill_chunks`, `requests_completed`): a probe is a MISS when the
replica holds live work (`queue_depth > 0`) but none of its progress
counters advanced since the previous probe.  `miss_budget` consecutive
misses mark the replica DEAD — ineligible exactly like an OPEN breaker,
and the router then treats it as crashed (removes it and resumes its
in-flight requests elsewhere from their `ResumeDescriptor`s).

Probes are clock-gated by `probe_interval_ms` (0 = probe on every call —
the deterministic CI setting); the clock is injectable so tests drive
time explicitly.  `fleet.probe.flap` injects a FALSE miss into one
probe evaluation: a single flap must be absorbed by the miss budget
(no state change beyond SUSPECT), while a persistent flap must escalate
to DEAD and a successful failover — both are tested contracts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from easydist_tpu.resilience import faultinject

__all__ = ["ALIVE", "SUSPECT", "DEAD", "HealthConfig", "HealthMonitor"]

ALIVE = "alive"
SUSPECT = "suspect"   # >=1 consecutive miss, budget not exhausted
DEAD = "dead"

# progress counters whose sum forms the liveness heartbeat
_PROGRESS_COUNTERS = ("decode_steps", "prefill_chunks",
                      "requests_completed")


@dataclass(frozen=True)
class HealthConfig:
    """probe_interval_ms: min wall-clock between probe rounds (0 probes
    every call — deterministic tests/CI).  miss_budget: consecutive
    missed probes before a replica is declared DEAD (>=1; a budget of 1
    tolerates zero flaps, so keep it >=2 where probes can race work)."""
    probe_interval_ms: float = 0.0
    miss_budget: int = 3

    def __post_init__(self):
        if self.miss_budget < 1:
            raise ValueError(
                f"miss_budget must be >= 1, got {self.miss_budget}")
        if self.probe_interval_ms < 0:
            raise ValueError("probe_interval_ms must be >= 0")


class _ReplicaHealth:
    __slots__ = ("state", "misses", "last_progress")

    def __init__(self):
        self.state = ALIVE
        self.misses = 0
        self.last_progress: Optional[int] = None


class HealthMonitor:
    """Tracks ALIVE/SUSPECT/DEAD per replica id.

    The router drives `probe()` once per fleet step; `mark_dead()` is the
    immediate path for replicas whose step() raised.  DEAD is sticky
    until `revive()` (the router calls it from `add_replica`, so
    re-registering a replica id is the revive operation)."""

    def __init__(self, config: Optional[HealthConfig] = None, *,
                 clock: Callable[[], float] = None):
        import time

        self.config = config or HealthConfig()
        self._clock = clock or time.monotonic
        self._replicas: Dict[str, _ReplicaHealth] = {}
        self._last_probe_t: Optional[float] = None
        # bounded transition log: (replica_id, state, reason)
        self.events: List[Dict[str, str]] = []
        self._event_cap = 256

    # ------------------------------------------------------------ tracking
    def track(self, replica_id: str) -> None:
        self._replicas.setdefault(replica_id, _ReplicaHealth())

    def drop(self, replica_id: str) -> None:
        """Forget a replica (clean removal after drain or crash
        recovery); its DEAD tombstone is recorded in `events`."""
        self._replicas.pop(replica_id, None)

    def revive(self, replica_id: str) -> None:
        """Reset state for a re-registered replica id (a fresh session
        joining under a previously-crashed id)."""
        prev = self._replicas.get(replica_id)
        if prev is not None and prev.state != ALIVE:
            self._event(replica_id, ALIVE, "revived")
        self._replicas[replica_id] = _ReplicaHealth()

    def state(self, replica_id: str) -> str:
        h = self._replicas.get(replica_id)
        return h.state if h is not None else ALIVE

    def mark_dead(self, replica_id: str, reason: str = "crash") -> None:
        self.track(replica_id)
        h = self._replicas[replica_id]
        if h.state != DEAD:
            h.state = DEAD
            self._event(replica_id, DEAD, reason)

    # -------------------------------------------------------------- probing
    def probe(self, replicas) -> List[str]:
        """One probe round over `replicas` (objects exposing
        `.replica_id` and `.session`); returns replica ids newly DEAD
        this round.  Clock-gated by probe_interval_ms; 0 never skips."""
        now = self._clock()
        interval = self.config.probe_interval_ms / 1e3
        if interval > 0 and self._last_probe_t is not None \
                and now - self._last_probe_t < interval:
            return []
        self._last_probe_t = now
        newly_dead: List[str] = []
        for rep in sorted(replicas, key=lambda r: r.replica_id):
            rid = rep.replica_id
            self.track(rid)
            h = self._replicas[rid]
            if h.state == DEAD:
                continue
            progress = sum(rep.session.metrics.counter(c)
                           for c in _PROGRESS_COUNTERS)
            advanced = (h.last_progress is None
                        or progress > h.last_progress)
            h.last_progress = progress
            # flap: the probe itself lies about progress this one time
            if faultinject.fire("fleet.probe.flap"):
                advanced = False
            if advanced or rep.session.queue_depth == 0:
                # progressing, or idle (an idle replica SHOULD not move)
                if h.misses and h.state == SUSPECT:
                    self._event(rid, ALIVE, "progress resumed")
                h.misses = 0
                h.state = ALIVE
                continue
            h.misses += 1
            if h.misses >= self.config.miss_budget:
                h.state = DEAD
                self._event(rid, DEAD,
                            f"{h.misses} consecutive missed probes "
                            f"with queue_depth > 0")
                newly_dead.append(rid)
            elif h.state != SUSPECT:
                h.state = SUSPECT
                self._event(rid, SUSPECT, "missed probe")
        return newly_dead

    # ------------------------------------------------------------ reporting
    def _event(self, rid: str, state: str, reason: str) -> None:
        self.events.append(
            {"replica_id": rid, "state": state, "reason": reason})
        del self.events[:-self._event_cap]

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        return {rid: {"state": h.state, "misses": h.misses}
                for rid, h in self._replicas.items()}

    def transitions(self) -> List[Dict[str, str]]:
        """The state-transition event stream, oldest first — the
        layer-12 conformance surface `analyze.modelcheck.
        replay_health_events` validates against the HealthSpec's
        admitted relation (PROTO003).  Every replica starts ALIVE
        (track()), so the events alone determine each step's
        (from, to) edge."""
        return list(self.events)
