"""Fleet serving: N `GenerationSession` replicas behind one router.

Scales serve/ horizontally without touching the bitwise spine:

  * `FleetRouter` — admission-controlled submit routed by a scored policy
    (prefix-cache affinity + slot occupancy, breaker-aware eligibility,
    consistent-hash placement for cold prefixes), replica lifecycle
    (zero-downtime drain with hot-page migration, immediate evacuate with
    bitwise-seamless mid-stream resubmission);
  * prefill/decode disaggregation — dedicated prefill replicas compute
    page-aligned prompt prefixes and hand committed KV pages to decode
    replicas through a `KVTransport`;
  * `InProcessTransport` — reference transport: pages move by reference,
    the sha256-per-page manifest (checkpoint MANIFEST.json idiom) still
    round-trips and is verified before commit (FLEET002);
  * `HashRing` — classic consistent hashing over sha256 virtual points,
    so cold-prefix placement is sticky and a drain only remaps ~1/N of
    the keyspace.

Fault tolerance (this layer's tested contract, not an aspiration):

  * `HealthMonitor` — step-liveness heartbeat over the counters every
    session already keeps; a replica holding live work whose counters
    stop advancing goes SUSPECT then DEAD (ineligible like an OPEN
    breaker) and the router fails it over;
  * `ResumeDescriptor` — per-request emitted-token mirror synced after
    every step; crash recovery resubmits prompt+ids to a survivor and
    the bitwise spine makes the continuation token-for-token identical;
  * hardened transport — `send_pages` adds deadline + jittered-backoff
    retry and idempotent manifest-keyed commits over verify-then-commit
    `transfer`; a half-arrived or bit-flipped page never enters a trie;
  * `PoisonRequestError` — a request that crashes `quarantine_after`
    distinct replicas is rejected structurally instead of rolling
    through the fleet.

Fleet outputs are bitwise-identical to a single session's: all replicas
run the same params/programs, prefix restore equals recompute, and greedy
continuation is a pure function of the token prefix.  docs/SERVING.md
covers the design; FLEET001-005 in docs/ANALYZE.md are the audits and
docs/RESILIENCE.md §7 the fault catalog.
"""

from .failover import PoisonRequestError, ResumeDescriptor  # noqa: F401
from .hashring import HashRing, prefix_hash_key  # noqa: F401
from .health import (ALIVE, DEAD, SUSPECT, HealthConfig,  # noqa: F401
                     HealthMonitor)
from .router import FleetConfig, FleetRouter, Replica  # noqa: F401
from .transport import (InProcessTransport, KVTransport,  # noqa: F401
                        PageCorruptError, TransportError,
                        TransportStallError, manifest_key, page_manifest,
                        verify_manifest)
