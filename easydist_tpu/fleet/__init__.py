"""Fleet serving: N `GenerationSession` replicas behind one router.

Scales serve/ horizontally without touching the bitwise spine:

  * `FleetRouter` — admission-controlled submit routed by a scored policy
    (prefix-cache affinity + slot occupancy, breaker-aware eligibility,
    consistent-hash placement for cold prefixes), replica lifecycle
    (zero-downtime drain with hot-page migration, immediate evacuate with
    bitwise-seamless mid-stream resubmission);
  * prefill/decode disaggregation — dedicated prefill replicas compute
    page-aligned prompt prefixes and hand committed KV pages to decode
    replicas through a `KVTransport`;
  * `InProcessTransport` — reference transport: pages move by reference,
    the sha256-per-page manifest (checkpoint MANIFEST.json idiom) still
    round-trips and is verified before commit (FLEET002);
  * `HashRing` — classic consistent hashing over sha256 virtual points,
    so cold-prefix placement is sticky and a drain only remaps ~1/N of
    the keyspace.

Fleet outputs are bitwise-identical to a single session's: all replicas
run the same params/programs, prefix restore equals recompute, and greedy
continuation is a pure function of the token prefix.  docs/SERVING.md
covers the design; FLEET001-003 in docs/ANALYZE.md are the audits.
"""

from .hashring import HashRing, prefix_hash_key  # noqa: F401
from .router import FleetConfig, FleetRouter, Replica  # noqa: F401
from .transport import (InProcessTransport, KVTransport,  # noqa: F401
                        page_manifest, verify_manifest)
