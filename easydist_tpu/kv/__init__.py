"""Paged KV subsystem: a refcounted page-pool allocator over one
preallocated HBM arena plus an int32 page-table indirection per live
sequence.

The slot-pool decode cache (serve/generation.py PR 8-11) pads every
sequence to its bucket's max_len, so HBM per slot is worst-case and
occupancy caps out under mixed-length traffic.  This package makes the
fixed-size `page_tokens`-token KV page — already the unit the prefix trie
commits and the fleet transport ships — THE allocation unit for decode
storage too:

  * `pool.PagePool` — host-side free-list allocator with per-page
    refcounts.  Copy-on-write sharing with the prefix trie: a restored
    prefix MAPS its committed pages into the sequence's page table
    (refcount bump) instead of `dynamic_update_slice`-copying bytes, and
    serving never writes a shared page (writes land at positions past the
    restored prefix, in freshly allocated pages), so the "copy" half of
    COW never runs on the serving path.
  * `table.PageTable` — per-slot int32 page indices, fixed
    [max_slots, max_pages] shape so the compiled decode step's signature
    stays closed over arbitrary sequence lengths.  Unmapped entries hold
    the sentinel `n_pages` (one past the arena): scatter writes through a
    sentinel drop (`mode="drop"`), gathers clip and the garbage row is
    masked to -inf before softmax.

Arena layout matches the bucketed cache with pages replacing the batch
axis — {"k","v"}: [layers, n_pages, (kv_)heads, page_tokens, head_dim] —
so `kv_cache_specs` shards heads on "tp" identically for both layouts.
Analyze rule KV001 (`analyze/kv_rules.py`) audits the pool/table/trie
bookkeeping; `check_invariants` here is the raw audit it wraps.
"""

from __future__ import annotations

from .pool import PagePool
from .table import PageTable
from .tier import HostTier, TierError

__all__ = ["HostTier", "PagePool", "PageTable", "TierError",
           "is_host_ref", "is_page_ref"]


def is_page_ref(kv) -> bool:
    """True iff a trie-committed kv value is a page REFERENCE
    (`{"page": id}`) rather than materialized arrays.

    This is the paged layout's aliasing contract: the arena is donated
    to every compiled dispatch, so host bookkeeping (trie nodes, resume
    descriptors, transport manifests) must hold *indices into* the
    arena, never the arena arrays themselves — a retained array
    reference is storage the next donating dispatch invalidates
    (analyze layer 11, ALIAS004)."""
    return isinstance(kv, dict) and set(kv) == {"page"}


def is_host_ref(kv) -> bool:
    """True iff a trie-committed kv value was DEMOTED to the host tier
    (`{"host": key}`, `tier.HostTier` holding the bytes).  Host refs own
    no arena page: the trie node charges 0 bytes against the HBM budget
    and promotion (tier.get + arena import) swaps the value back to a
    `{"page": id}` ref before the slot's first decode step."""
    return isinstance(kv, dict) and set(kv) == {"host"}
