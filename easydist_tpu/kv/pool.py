"""Refcounted page-pool allocator over one preallocated HBM arena.

`PagePool` is pure host bookkeeping: the device arena (a {"k","v"} pytree
of [layers, n_pages, heads, page_tokens, head_dim] arrays) is allocated
once by the owner (GenerationSession via `models.init_kv_pages`) and
threaded through compiled steps as a donated argument; the pool tracks
which of its `n_pages` page slots are free, how many holders reference
each live page, and the utilization counters serving metrics report.

Refcount semantics: a page's count is (# live sequences whose page table
maps it) + (1 if the prefix trie holds a committed node for it).  `alloc`
hands out a free page at refcount 1; `share` bumps (trie commit, prefix
restore, fleet import of an already-present page); `release` drops and
reclaims at zero.  Shared pages are never written by serving (restored
prefixes are whole aligned pages; writes only land at positions past the
prefix, in pages the sequence allocated itself), so sharing needs no
device copy — `ensure_exclusive` exists for callers that DO intend to
write (stress tests, future in-place migration) and is the copy-on-write
fault point.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["PagePool"]


class PagePool:
    """Free-list allocator for `n_pages` fixed `page_tokens`-token KV
    pages of `page_bytes` bytes each (k + v, all layers)."""

    def __init__(self, n_pages: int, page_tokens: int, page_bytes: int = 0):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        if page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, got {page_tokens}")
        if page_bytes < 0:
            raise ValueError(f"page_bytes must be >= 0, got {page_bytes}")
        self.n_pages = n_pages
        self.page_tokens = page_tokens
        self.page_bytes = page_bytes
        # LIFO free list: recently freed pages are reused first, keeping
        # the hot working set of arena rows small
        self._free: List[int] = list(range(n_pages - 1, -1, -1))
        self._refcount: List[int] = [0] * n_pages
        self.allocs = 0
        self.frees = 0
        self.shares = 0
        self.peak_in_use = 0

    # ---------------------------------------------------------- allocation
    @property
    def sentinel(self) -> int:
        """The never-valid page id page tables use for unmapped entries:
        one past the arena, so scatter-with-drop ignores writes through it
        and clipped gathers read a real (masked) row."""
        return self.n_pages

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.n_pages - len(self._free)

    def alloc(self) -> int:
        """Pop a free page at refcount 1.  Raises when the arena is
        exhausted — callers gate on `n_free` (admission reserves a
        sequence's worst-case pages up front, evicting unpinned trie
        nodes first), so hitting this is a bookkeeping bug."""
        if not self._free:
            raise RuntimeError(
                f"page pool exhausted: all {self.n_pages} pages live "
                f"(admission should have reserved before allocating)")
        page = self._free.pop()
        self._refcount[page] = 1
        self.allocs += 1
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return page

    def share(self, page: int) -> int:
        """Add a holder to a live page (prefix restore mapping it into
        another sequence's table, trie commit, fleet import hit).
        Returns the new refcount."""
        self._check_live(page, "share")
        self._refcount[page] += 1
        self.shares += 1
        return self._refcount[page]

    def release(self, page: int) -> int:
        """Drop one holder; the page returns to the free list when the
        last holder releases.  Returns the remaining refcount."""
        self._check_live(page, "release")
        self._refcount[page] -= 1
        if self._refcount[page] == 0:
            self._free.append(page)
            self.frees += 1
        return self._refcount[page]

    def refcount(self, page: int) -> int:
        if not 0 <= page < self.n_pages:
            raise ValueError(f"page {page} out of range [0, {self.n_pages})")
        return self._refcount[page]

    def ensure_exclusive(self, page: int) -> Optional[int]:
        """Copy-on-write fault point: if `page` is shared (refcount > 1),
        allocate a fresh page for the caller to copy into and drop the
        caller's hold on the shared one; return the new page id.  Returns
        None when the page is already exclusive.  The serving path never
        triggers this (it never writes shared pages); stress tests and
        future in-place migration do."""
        self._check_live(page, "ensure_exclusive")
        if self._refcount[page] == 1:
            return None
        fresh = self.alloc()
        self.release(page)
        return fresh

    def _check_live(self, page: int, op: str) -> None:
        if not 0 <= page < self.n_pages:
            raise ValueError(
                f"{op}: page {page} out of range [0, {self.n_pages})")
        if self._refcount[page] <= 0:
            raise ValueError(f"{op}: page {page} is free (refcount "
                             f"{self._refcount[page]}) — use-after-free")

    # ----------------------------------------------------------- reporting
    def stats(self) -> Dict[str, int]:
        return {"n_pages": self.n_pages, "page_tokens": self.page_tokens,
                "page_bytes": self.page_bytes, "in_use": self.in_use,
                "free": self.n_free, "allocs": self.allocs,
                "frees": self.frees, "shares": self.shares,
                "peak_in_use": self.peak_in_use}

    def check_invariants(self) -> List[str]:
        """Refcount/byte audit (analyze KV001 wraps these into findings):
        free-list entries must be unique in-range pages at refcount 0,
        live pages must hold positive counts, and the arena byte total
        must equal mapped + free page bytes (conservation — no page is
        both free and mapped, none is lost)."""
        problems: List[str] = []
        seen = set()
        for page in self._free:
            if not 0 <= page < self.n_pages:
                problems.append(f"free list holds out-of-range page {page}")
                continue
            if page in seen:
                problems.append(f"free list holds page {page} twice "
                                f"(double free)")
            seen.add(page)
            if self._refcount[page] != 0:
                problems.append(
                    f"free page {page} has refcount {self._refcount[page]} "
                    f"(freed while still referenced)")
        for page in range(self.n_pages):
            if page not in seen and self._refcount[page] <= 0:
                problems.append(
                    f"page {page} has refcount {self._refcount[page]} but "
                    f"is not on the free list (leaked page)")
        arena_bytes = self.n_pages * self.page_bytes
        accounted = (self.in_use + self.n_free) * self.page_bytes
        if arena_bytes != accounted:
            problems.append(
                f"byte conservation drift: arena {arena_bytes} != "
                f"mapped+free {accounted}")
        return problems
