"""Int32 page-table indirection: which arena page holds each
`page_tokens`-token window of each live sequence.

The table is a host-side numpy array of fixed shape
[max_slots, max_pages] — the compiled decode step takes it (as a device
int32 array) every step, and the FIXED shape is what keeps the step's
signature closed: a sequence at length 37 and one at length 1988 present
the same table shape, only the entries differ.  Unmapped entries hold the
sentinel `n_pages` (one past the arena): compiled scatter writes through
the table use `mode="drop"` so sentinel writes vanish deterministically,
and gathers clip to the last real page whose rows the attention mask
zeroes out before softmax.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["PageTable"]


class PageTable:
    """[max_slots, max_pages] int32 page indices, sentinel `n_pages` for
    unmapped entries.  Pure host bookkeeping — callers push `self.array`
    to device each step (a few KiB; the arena itself never moves)."""

    def __init__(self, max_slots: int, max_pages: int, n_pages: int):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_pages < 1:
            raise ValueError(f"max_pages must be >= 1, got {max_pages}")
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        self.max_slots = max_slots
        self.max_pages = max_pages
        self.n_pages = n_pages
        self.sentinel = n_pages
        self.array = np.full((max_slots, max_pages), self.sentinel,
                             dtype=np.int32)

    # ------------------------------------------------------------- mapping
    def map(self, slot: int, idx: int, page: int) -> None:
        """Point `slot`'s window `idx` (tokens [idx*pt, (idx+1)*pt)) at
        arena `page`.  Windows must be mapped at most once — remapping a
        live entry would leak its page's refcount."""
        if not 0 <= page < self.n_pages:
            raise ValueError(f"page {page} out of range [0, {self.n_pages})")
        if self.array[slot, idx] != self.sentinel:
            raise ValueError(
                f"slot {slot} window {idx} already maps page "
                f"{int(self.array[slot, idx])} (unmap before remapping)")
        self.array[slot, idx] = page

    def unmap_row(self, slot: int) -> List[int]:
        """Clear `slot`'s row back to sentinel, returning the pages it
        mapped (the caller releases each against the pool)."""
        row = self.array[slot]
        pages = [int(p) for p in row[row != self.sentinel]]
        row[:] = self.sentinel
        return pages

    def unmap_tail(self, slot: int, from_idx: int) -> List[int]:
        """Clear `slot`'s windows >= `from_idx` back to sentinel,
        returning the pages they mapped (the caller releases each
        against the pool).  This is the speculative-decoding rollback
        primitive: windows mapped beyond a sequence's up-front
        reservation only ever hold rejected draft rows, so truncating
        the table tail releases them without touching the committed
        prefix — the hole-free-prefix invariant holds trivially (a
        suffix clear cannot create a hole)."""
        if from_idx < 0:
            raise ValueError(f"from_idx must be >= 0, got {from_idx}")
        tail = self.array[slot, from_idx:]
        pages = [int(p) for p in tail[tail != self.sentinel]]
        tail[:] = self.sentinel
        return pages

    def mapped(self, slot: int) -> List[int]:
        """Pages `slot` currently maps, in window order."""
        row = self.array[slot]
        return [int(p) for p in row[row != self.sentinel]]

    def n_mapped(self, slot: int) -> int:
        return int((self.array[slot] != self.sentinel).sum())

    # ----------------------------------------------------------- reporting
    def check_invariants(self) -> List[str]:
        """Shape/range audit (KV001 cross-checks entries against the
        pool's refcounts; this is the table-local half)."""
        problems: List[str] = []
        if self.array.shape != (self.max_slots, self.max_pages):
            problems.append(
                f"table shape drifted to {self.array.shape} (compiled-step "
                f"signature no longer closed)")
        bad = (self.array < 0) | (self.array > self.sentinel)
        if bad.any():
            problems.append(
                f"{int(bad.sum())} entries outside [0, {self.sentinel}]")
        for slot in range(self.max_slots):
            row = self.array[slot]
            live = row != self.sentinel
            # mapped windows must be a contiguous prefix of the row: a
            # hole would mean attention gathers a garbage page INSIDE the
            # live length, where the mask does not cover for it
            if live.any():
                last = int(np.max(np.nonzero(live)[0]))
                if not live[:last + 1].all():
                    problems.append(
                        f"slot {slot} has unmapped window before window "
                        f"{last} (hole inside the live prefix)")
        return problems
