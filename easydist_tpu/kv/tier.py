"""Host memory tier for cold prefix-trie KV pages.

HBM caps the prefix trie's working set: every committed page the trie
holds is an arena page a live sequence cannot use, so under memory
pressure the trie evicts — and the next prompt sharing that prefix pays
full prefill again.  This module adds the layer below HBM: cold unpinned
trie pages DEMOTE to host numpy over the chunked, RESHARD001-audited
`reshard.fetch_chunked` substrate (no transfer ever stages more than one
chunk), and PROMOTE back into a freshly allocated arena page on the next
trie hit, before the slot's first decode step.  The prefix cache's
effective capacity becomes host-RAM-bound, not HBM-bound.

Integrity contract — the same idiom as `fleet/transport.py`:

  * every demoted page carries a sha256 **manifest** over its fetched
    bytes (per leaf: name, dtype, shape, raw buffer).  The digest is
    computed from the first clean fetch while the HBM page still exists,
    so a corrupt host copy detected at verification time can simply be
    **refetched** (drilled by the `kv.tier.fetch_corrupt` fault point);
  * promotion re-verifies the manifest before any byte re-enters the
    arena — a mismatch drops the entry and surfaces as a trie miss (and
    an analyze KVQ003 finding), never as silently corrupt KV;
  * demotion/promotion move EXACT bytes (quantized pages ship payload
    AND scales), so a tier round trip is bitwise — the exact-dtype
    serving path stays bitwise with the tier on.

Degradation: a failed host allocation (`kv.tier.host_oom` fault point)
pauses demotion hold-and-warn style — serving continues with plain trie
eviction, losing capacity, never correctness.  The host store itself is
LRU-evicted under `byte_budget`.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Dict, List, Optional

import numpy as np

from easydist_tpu.resilience import faultinject

logger = logging.getLogger(__name__)

__all__ = ["HostTier", "TierError", "page_digest"]


class TierError(RuntimeError):
    """A tier entry failed its manifest check (callers treat as a miss)."""


def page_digest(arrays: Dict[str, np.ndarray]) -> str:
    """sha256 over a page's host leaves in sorted-key order (name, dtype,
    shape, raw bytes per leaf) — the per-page manifest.  Quantized pages
    include their scale leaves automatically, so a scale/payload desync
    cannot verify."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class _Entry:
    __slots__ = ("arrays", "digest", "nbytes", "tick")

    def __init__(self, arrays, digest, nbytes, tick):
        self.arrays = arrays
        self.digest = digest
        self.nbytes = nbytes
        self.tick = tick


class HostTier:
    """LRU host store of demoted KV pages under `byte_budget` bytes.

    `put` fetches each device leaf through `reshard.fetch_chunked`
    (chunk-bounded staging), manifests the result, and verifies the host
    copy against the manifest while the source page still exists — a
    corrupt copy refetches once (`kv.tier.fetch_corrupt` drill) before
    giving up.  `get` re-verifies the manifest and raises `TierError` on
    mismatch (the entry is dropped; the caller recomputes).  All methods
    are thread-safe for the session's single-writer use."""

    def __init__(self, byte_budget: int, chunk_bytes: Optional[int] = None):
        if byte_budget < 0:
            raise ValueError(f"byte_budget must be >= 0, got {byte_budget}")
        self.byte_budget = byte_budget
        self.chunk_bytes = chunk_bytes
        self.paused = False
        self._lock = threading.Lock()
        self._entries: Dict[object, _Entry] = {}
        self._tick = 0
        self.bytes_used = 0
        self.demotions = 0
        self.promotions = 0
        self.fetch_retries = 0
        self.manifest_failures = 0
        self.host_evictions = 0

    # ------------------------------------------------------------- demote
    def _fetch_leaf(self, x, label: str) -> np.ndarray:
        """One leaf device -> host with the corrupt-fetch drill: the
        manifest digest comes from the transfer, the verification
        re-check catches an injected post-transfer corruption, and the
        refetch succeeds because the HBM page is still live at demotion
        time."""
        from easydist_tpu.reshard import fetch_chunked

        for attempt in (1, 2):
            host = fetch_chunked(x, chunk_bytes=self.chunk_bytes,
                                 node=f"kv.tier[{label}]")
            digest = hashlib.sha256(
                np.ascontiguousarray(host).tobytes()).hexdigest()
            if faultinject.fire("kv.tier.fetch_corrupt"):
                # simulated in-flight corruption of the host copy (bad
                # DMA / bit rot between transfer and store)
                host = np.array(host, copy=True)
                flat = host.reshape(-1).view(np.uint8)
                flat[0] ^= 0xFF
            check = hashlib.sha256(
                np.ascontiguousarray(host).tobytes()).hexdigest()
            if check == digest:
                return host
            self.fetch_retries += 1
            logger.warning("[kv.tier] corrupt fetch of %s caught by "
                           "manifest (attempt %d); refetching", label,
                           attempt)
        raise TierError(f"leaf {label} failed manifest verification "
                        f"twice during demotion")

    def put(self, key, arrays: Dict[str, object]) -> bool:
        """Demote one page (dict of device arrays) under `key`.  Returns
        False without storing when the tier is paused, the budget is 0,
        the page exceeds the whole budget, or a host allocation fails
        (which also pauses the tier hold-and-warn style)."""
        if self.paused or self.byte_budget == 0:
            return False
        try:
            if faultinject.fire("kv.tier.host_oom"):
                raise MemoryError("injected host allocation failure")
            host = {name: self._fetch_leaf(arrays[name], f"{key}:{name}")
                    for name in sorted(arrays)}
        except MemoryError as e:
            self.paused = True
            logger.warning("[kv.tier] host allocation failed (%s); "
                           "demotion PAUSED — serving continues with "
                           "plain trie eviction", e)
            return False
        nbytes = sum(int(a.nbytes) for a in host.values())
        if nbytes > self.byte_budget:
            return False
        with self._lock:
            self._evict_to(self.byte_budget - nbytes)
            if self.bytes_used + nbytes > self.byte_budget:
                return False
            self._tick += 1
            self._entries[key] = _Entry(host, page_digest(host), nbytes,
                                        self._tick)
            self.bytes_used += nbytes
            self.demotions += 1
        return True

    def _evict_to(self, budget: int) -> None:
        while self.bytes_used > budget and self._entries:
            victim = min(self._entries, key=lambda k: self._entries[k].tick)
            self.bytes_used -= self._entries.pop(victim).nbytes
            self.host_evictions += 1

    # ------------------------------------------------------------ promote
    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def get(self, key) -> Dict[str, np.ndarray]:
        """Promote-read one page: manifest-verify and return the host
        arrays (the entry stays until `drop`).  Raises `KeyError` for an
        unknown key and `TierError` (after dropping the entry) when the
        stored bytes no longer match the manifest."""
        with self._lock:
            entry = self._entries[key]
            self._tick += 1
            entry.tick = self._tick
        if page_digest(entry.arrays) != entry.digest:
            with self._lock:
                if key in self._entries:
                    self.bytes_used -= self._entries.pop(key).nbytes
                self.manifest_failures += 1
            raise TierError(f"tier entry {key!r} failed manifest "
                            f"verification at promotion")
        self.promotions += 1
        return entry.arrays

    def drop(self, key) -> None:
        """Forget one entry (after promotion moved it back to HBM, or
        when its trie node is evicted outright)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self.bytes_used -= entry.nbytes

    def resume(self) -> None:
        """Lift a `host_oom` pause (operator action after freeing RAM)."""
        self.paused = False

    # ---------------------------------------------------------- reporting
    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes_used": self.bytes_used,
                    "byte_budget": self.byte_budget,
                    "demotions": self.demotions,
                    "promotions": self.promotions,
                    "fetch_retries": self.fetch_retries,
                    "manifest_failures": self.manifest_failures,
                    "host_evictions": self.host_evictions,
                    "paused": self.paused}

    def check_invariants(self) -> List[str]:
        """Byte-accounting + manifest audit (analyze KVQ003 wraps these
        into findings)."""
        problems: List[str] = []
        with self._lock:
            entries = dict(self._entries)
            counted = self.bytes_used
        seen = 0
        for key, entry in entries.items():
            seen += entry.nbytes
            if page_digest(entry.arrays) != entry.digest:
                problems.append(
                    f"tier entry {key!r}: stored bytes disagree with the "
                    f"sha256 manifest (host corruption — promotion would "
                    f"serve wrong KV)")
        if seen != counted:
            problems.append(
                f"tier byte accounting drift: counter {counted} != sum "
                f"of entries {seen}")
        return problems
