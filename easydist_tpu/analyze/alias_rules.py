"""Layer 11: donation/aliasing sanitizer.

Every hot serving path leans on buffer donation for in-place XLA updates
— the paged decode arena (arg0 <-> out0), the chunked-prefill staging
caches, the speculative verify step — but tier-1 runs JAX_PLATFORMS=cpu,
where JAX silently IGNORES donation.  A use-after-donate or a
double-donate therefore passes every CPU test bitwise and corrupts HBM
silently on real TPUs.  This layer catches the hazard statically, at
three altitudes:

ALIAS001 — use of a donated invar after its consuming dispatch.  Two
    forms: (a) a traced driver program whose inner `pjit` equation
    donates a var that a LATER equation (or the program output) still
    reads; (b) the `ast` host-code lint (`lint_host_donation`), which
    flags a retained Python reference to a donated argument loaded
    after the donating call without an intervening rebind.  The repo's
    rebind idiom — `pool.cache, tok = self._decode_c(pool.cache, ...)`
    — is the clean shape: the Store on the call's own statement retires
    the stale reference immediately.

ALIAS002 — double donation: one underlying buffer donated through two
    invar positions of one dispatch, or two state outputs claiming the
    same donated input (`state_pairs` with duplicate input indices).
    XLA hands the storage out twice; whichever write lands second
    clobbers the other.

ALIAS003 — donation declared but unhonorable: the donated input matches
    no output's shape/dtype, so XLA silently COPIES instead of aliasing
    (the `jax.jit` runtime only warns, and only on backends that honor
    donation at all).  The in-place economics the donation was written
    for never happen; at cache scale that is a full HBM copy per step.

ALIAS004 — a donated device buffer still reachable from a live host
    reference across a step boundary: an inflight snapshot, a hot-page
    export, or a prefix-trie node holding a staging row by reference
    rather than by copy.  The next donating dispatch invalidates
    storage the host still intends to read.  The check is identity
    based (`is` over array leaves), run by `serve.generation` at the
    same checkpoint as the donation audits.

The AST lint intentionally reasons per function scope and in source-line
order (no interprocedural or loop-carried dataflow): the donation
convention here is strictly local — compiled callables named `*_c` (or
bound from `easydist_compile(...)`) donate positional arg 0 — so a
scope-local "donate, then load without rebind" walk catches the real
bug class without drowning the driver's baseline in speculative flow
analysis.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .findings import Finding, make_finding

# ----------------------------------------------------------- jaxpr pass


def _donated_flags(eqn) -> Tuple[bool, ...]:
    """The eqn's donation vector, aligned with its invars (pjit carries
    `donated_invars`; every other primitive donates nothing)."""
    params = getattr(eqn, "params", None)
    if not isinstance(params, dict):
        return ()
    flags = params.get("donated_invars")
    if not flags:
        return ()
    return tuple(bool(b) for b in flags)


def _sub_jaxprs(eqn):
    for param in getattr(eqn, "params", {}).values():
        if hasattr(param, "jaxpr"):
            yield param.jaxpr
        elif isinstance(param, (list, tuple)):
            for p in param:
                if hasattr(p, "jaxpr"):
                    yield p.jaxpr


def _aval_sig(var):
    aval = getattr(var, "aval", None)
    return (tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "?")))


def audit_jaxpr_donation(jaxpr, node: str = "program",
                         check_unhonored: bool = True) -> List[Finding]:
    """ALIAS001/002/003 over one (possibly nested) jaxpr: for every
    equation carrying a `donated_invars` vector,

    * ALIAS001 — a donated var read by any LATER equation or appearing
      in the enclosing jaxpr's outvars (the dispatch freed it; the
      program still uses it);
    * ALIAS002 — one var bound to two invar positions of the same
      equation with at least one position donated (the buffer aliases
      itself across the dispatch boundary);
    * ALIAS003 — a donated invar whose shape/dtype matches NO output of
      its equation (nothing can alias it, so XLA silently copies).

    Recurses into sub-jaxprs (pjit/cond/scan bodies).  One finding per
    (equation, hazard) — a var both double-donated and reused later
    reports each hazard once, not per use.  `check_unhonored=False`
    skips the ALIAS003 arm (CompileResult.analyze passes it because
    `audit_donation_pairs` already audits the top-level dispatch's
    honorability with the state-pair context attached).
    """
    from jax._src import core as jex_core

    findings: List[Finding] = []
    eqns = list(jaxpr.eqns)
    out_vars = [v for v in jaxpr.outvars
                if not isinstance(v, jex_core.Literal)]
    for k, eqn in enumerate(eqns):
        flags = _donated_flags(eqn)
        if any(flags):
            prim = getattr(eqn.primitive, "name", "eqn")
            invars = list(eqn.invars)
            donated = [(i, invars[i]) for i, f in enumerate(flags)
                       if f and i < len(invars)
                       and not isinstance(invars[i], jex_core.Literal)]
            # ALIAS002: one var, >=2 invar positions, >=1 donated
            seen_dup = set()
            for i, v in donated:
                if v in seen_dup:
                    continue
                positions = [j for j, u in enumerate(invars) if u is v]
                if len(positions) > 1:
                    seen_dup.add(v)
                    findings.append(make_finding(
                        "ALIAS002", node,
                        f"eqn {k} ({prim}): var {v} feeds invar positions "
                        f"{positions} with position {i} donated — XLA may "
                        f"overwrite the buffer while another operand "
                        f"still reads it"))
            # ALIAS001: donated var alive after the dispatch
            later_reads = set()
            for later in eqns[k + 1:]:
                later_reads.update(u for u in later.invars
                                   if not isinstance(u, jex_core.Literal))
            for i, v in donated:
                if v in later_reads or any(v is o for o in out_vars):
                    where = ("the program output" if any(
                        v is o for o in out_vars) else "a later equation")
                    findings.append(make_finding(
                        "ALIAS001", node,
                        f"eqn {k} ({prim}) donates invar {i} ({v}: "
                        f"{_aval_sig(v)[0]} {_aval_sig(v)[1]}) but "
                        f"{where} still reads it — bitwise-correct on "
                        f"CPU, silently corrupt where donation is "
                        f"honored"))
            # ALIAS003: donated invar with no alias-compatible output
            out_sigs = [_aval_sig(o) for o in eqn.outvars]
            for i, v in (donated if check_unhonored else ()):
                if _aval_sig(v) not in out_sigs:
                    findings.append(make_finding(
                        "ALIAS003", node,
                        f"eqn {k} ({prim}) donates invar {i} "
                        f"({_aval_sig(v)[0]} {_aval_sig(v)[1]}) but no "
                        f"output matches its shape/dtype — XLA silently "
                        f"copies instead of updating in place"))
        for sub in _sub_jaxprs(eqn):
            findings.extend(audit_jaxpr_donation(
                sub, node=node, check_unhonored=check_unhonored))
    return findings


# ---------------------------------------------------- CompileResult pass


def audit_donation_pairs(result, node: str = "compile") -> List[Finding]:
    """ALIAS002/003 over a CompileResult's state-threading declaration
    (`state_pairs`: flat output index -> flat input index, recorded by
    `_finish_compile`):

    * ALIAS002 — two outputs claim the same donated input (the donate
      set dedupes, so XLA sees one donation, but both callers believe
      they own the storage);
    * ALIAS003 — a pair whose output/input shape or dtype disagree, or
      whose indices fall outside the signature: the donation cannot be
      honored and XLA silently copies.  `infer_state_io`'s positional
      pairing cannot produce this (it requires identical leaf
      signatures); only an explicit `state_io` dict can.
    """
    pairs: Dict[int, int] = dict(getattr(result, "state_pairs", None) or {})
    donated = set(getattr(result, "donated_invars", ()) or ())
    if not pairs or not donated:
        return []
    findings: List[Finding] = []
    by_input: Dict[int, List[int]] = {}
    for out_idx, in_idx in pairs.items():
        by_input.setdefault(in_idx, []).append(out_idx)
    for in_idx, outs in sorted(by_input.items()):
        if in_idx in donated and len(outs) > 1:
            findings.append(make_finding(
                "ALIAS002", node,
                f"outputs {sorted(outs)} all claim donated input "
                f"{in_idx}: the buffer is handed out twice and one "
                f"state write clobbers the other"))
    in_avals = list(getattr(result, "in_avals", ()) or ())
    closed = getattr(result, "closed_jaxpr", None)
    out_avals = list(getattr(closed, "out_avals", ()) or ())
    for out_idx, in_idx in sorted(pairs.items()):
        if in_idx not in donated:
            continue
        if in_idx >= len(in_avals) or (out_avals
                                       and out_idx >= len(out_avals)):
            findings.append(make_finding(
                "ALIAS003", node,
                f"state pair out[{out_idx}] <- in[{in_idx}] indexes "
                f"outside the signature ({len(out_avals)} outputs, "
                f"{len(in_avals)} inputs): the declared donation can "
                f"never be honored"))
            continue
        if not out_avals:
            continue
        i_sig = (tuple(in_avals[in_idx].shape),
                 str(in_avals[in_idx].dtype))
        o_sig = (tuple(out_avals[out_idx].shape),
                 str(out_avals[out_idx].dtype))
        if i_sig != o_sig:
            findings.append(make_finding(
                "ALIAS003", node,
                f"state pair out[{out_idx}] {o_sig[0]} {o_sig[1]} <- "
                f"in[{in_idx}] {i_sig[0]} {i_sig[1]}: shape/dtype "
                f"mismatch, so XLA silently copies instead of donating "
                f"in place"))
    return findings


# ------------------------------------------------------ host-alias pass


def _array_leaves(tree) -> List[object]:
    """Array-like leaves only: identity comparison over Python scalars
    would false-positive on interned ints."""
    import jax

    return [l for l in jax.tree_util.tree_leaves(tree)
            if hasattr(l, "shape") and hasattr(l, "dtype")]


def audit_host_aliases(donated, holders,
                       node: str = "session") -> List[Finding]:
    """ALIAS004: identity overlap between donated device buffers and
    live host-held references.  `donated` maps a label (e.g. "cache",
    "staging", "arena") to a pytree whose array leaves the next
    dispatch will donate; `holders` maps a holder label (e.g.
    "snapshot", "trie", "hot_pages") to a pytree the host retains
    across the step boundary.  A holder leaf that IS (object identity)
    a donated leaf fires one aggregated finding per holder — the trie
    must hold `_extract` copies (bucketed) or page INDICES (paged),
    never the arena/staging arrays themselves.
    """
    donated_ids: Dict[int, str] = {}
    for label, tree in donated.items():
        for leaf in _array_leaves(tree):
            donated_ids.setdefault(id(leaf), label)
    if not donated_ids:
        return []
    findings: List[Finding] = []
    for holder, tree in holders.items():
        hit_labels = sorted({donated_ids[id(leaf)]
                             for leaf in _array_leaves(tree)
                             if id(leaf) in donated_ids})
        if hit_labels:
            findings.append(make_finding(
                "ALIAS004", node,
                f"host holder {holder!r} retains a reference to donated "
                f"buffer(s) {hit_labels} across the step boundary — the "
                f"next donating dispatch invalidates storage the host "
                f"still reads (hold a copy or an index, not the array)"))
    return findings


# ------------------------------------------------------- AST host lint

# a callee is "donating" when its terminal name matches this (the
# session's compiled-callable convention: _decode_c, _prefill_chunk_c,
# _paged_c("decode")(...), ...) or when it is a name bound from
# easydist_compile(...) in the same scope
_DONATING_NAME_RE = re.compile(r"^_[a-z0-9_]*_c$")
_COMPILE_FACTORIES = {"easydist_compile", "compile_step"}


def _callee_name(func_node) -> Optional[str]:
    if isinstance(func_node, ast.Attribute):
        return func_node.attr
    if isinstance(func_node, ast.Name):
        return func_node.id
    return None


def _expr_key(node) -> Optional[str]:
    """Stable identity of a Name/Attribute-chain expression (`buf`,
    `pool.cache`, `self.pool.staging`); None for anything else — only
    plain reference chains participate in the retained-reference walk."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_key(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


class _ScopeLint(ast.NodeVisitor):
    """One function scope's donate/store/load event streams, in source
    order.  Nested defs get their own scope (their bodies are skipped
    here and visited separately)."""

    def __init__(self):
        self.donates: List[Tuple[int, int, str]] = []  # (line, end, expr)
        self.stores: Dict[str, List[int]] = {}         # expr -> lines
        self.loads: Dict[str, List[Tuple[int, str]]] = {}
        self.compiled_names: set = set()

    # a nested def is its own scope (collected and visited separately);
    # class bodies stay in the enclosing stream so class-level wiring
    # still participates
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        # x = easydist_compile(...) binds a donating callable
        if isinstance(node.value, ast.Call):
            name = _callee_name(node.value.func)
            if name in _COMPILE_FACTORIES:
                for tgt in node.targets:
                    key = _expr_key(tgt)
                    if key:
                        self.compiled_names.add(key)
        self.generic_visit(node)

    def _is_donating_call(self, call: ast.Call) -> bool:
        name = _callee_name(call.func)
        if name is not None:
            return (_DONATING_NAME_RE.match(name) is not None
                    or _expr_key(call.func) in self.compiled_names
                    or name in self.compiled_names)
        if isinstance(call.func, ast.Call):
            # self._paged_c("decode")(arena, ...): the factory matched,
            # the returned callable donates
            inner = _callee_name(call.func.func)
            return (inner is not None
                    and _DONATING_NAME_RE.match(inner) is not None)
        return False

    def visit_Call(self, call):
        if self._is_donating_call(call) and call.args:
            key = _expr_key(call.args[0])
            if key is not None:
                end = getattr(call, "end_lineno", None) or call.lineno
                self.donates.append((call.lineno, end, key))
        self.generic_visit(call)

    def visit_Name(self, node):
        self._record(node, node.lineno)

    def visit_Attribute(self, node):
        key = _expr_key(node)
        if key is not None:
            self._record_key(node, key, node.lineno)
            return  # the chain is one event, not one per attribute hop
        self.generic_visit(node)

    def _record(self, node, line):
        key = _expr_key(node)
        if key is not None:
            self._record_key(node, key, line)

    def _record_key(self, node, key, line):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stores.setdefault(key, []).append(line)
        else:
            self.loads.setdefault(key, []).append((line, key))


def _scope_findings(scope: _ScopeLint, path: str,
                    node_label: str) -> List[Finding]:
    findings: List[Finding] = []
    for don_line, don_end, key in scope.donates:
        # the donation is live until the first rebind at or after the
        # donating statement (same-line rebind = the clean idiom); loads
        # inside the donating call's own line span ARE the call's
        # arguments, not stale reads
        rebinds = [ln for ln in scope.stores.get(key, ())
                   if ln >= don_line]
        first_rebind = min(rebinds) if rebinds else None
        stale = [ln for ln, _ in scope.loads.get(key, ())
                 if ln > don_end
                 and (first_rebind is None or ln < first_rebind)]
        if stale:
            line = min(stale)
            findings.append(make_finding(
                "ALIAS001", node_label,
                f"`{key}` is read after being donated on line "
                f"{don_line} with no intervening rebind — on donating "
                f"backends that storage is already invalid",
                path=path, line=line))
    return findings


def lint_file_donation(path: str, rel: Optional[str] = None,
                       source: Optional[str] = None) -> List[Finding]:
    """AST ALIAS001 host lint over one Python file.  Returns [] for
    unparsable files (the lint must never be the thing that fails)."""
    rel = rel or path
    if source is None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            return []
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError:
        return []
    findings: List[Finding] = []
    # module scope + every function scope, each analyzed independently
    scopes: List[Tuple[ast.AST, str]] = [(tree, "<module>")]
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append((n, n.name))
    for scope_node, label in scopes:
        lint = _ScopeLint()
        for stmt in scope_node.body:
            lint.visit(stmt)
        findings.extend(_scope_findings(lint, rel, f"{rel}:{label}"))
    return findings


def lint_host_donation(root: str,
                       subdirs: Iterable[str] = ("easydist_tpu",
                                                 "examples"),
                       ) -> List[Finding]:
    """The ALIAS001 host lint over every .py file beneath
    `root/<subdir>` (repo-relative paths on the findings, so baselines
    travel)."""
    findings: List[Finding] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                findings.extend(lint_file_donation(full, rel=rel))
    return findings
