"""Layer 3a: static verifier over the graph memory plan and remat rewrite.

Runs on the `(MetaGraph, MemoryPlan)` pair the planner produced
(`schedule/memory_planner.py`) plus the remat rewrite plan
(`schedule/remat.py`) — the whole memory pipeline whose errors otherwise
surface only as OOMs on real TPUs.  DistIR-style: everything here is pure
Python over already-built structures, no device execution.

  MEM001  independent liveness recomputation: every interval's
          (start, end) must match a producer/last-consumer audit done by a
          DIFFERENT traversal (operand scan vs the planner's edge lists),
          graph outputs pinned live to the program end;
  MEM002  sharded-bytes sizing: every interval's bytes must equal the
          placement-divided tensor size, element-aligned and rounded UP on
          non-divisible shard dims (the widest device's share);
  MEM003  skyline soundness: `offsets` overlap-free while live
          (`native.check_plan`), `peak_bytes` >= the sum-of-live lower
          bound, and `peak_bytes` == the packed extent max(offset+size);
  MEM004  HBM budget gate: the predicted per-device peak must fit the
          platform capacity (`edconfig.analyze_hbm_budget`, v5e default) —
          the finding carries a structured remat advisory naming which
          candidates, in `schedule/remat.py`'s largest-bytes-per-
          recompute-second order, would bring the program under budget;
  MEM005  remat-rewrite audit: every recomputed chain is pure flat
          primitives preceding its consumer, the post-rewrite planned peak
          is strictly lower, and the emitted program reads chain sources
          through `optimization_barrier` (no CSE fold-back).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from easydist_tpu import native
from easydist_tpu.metashard.metair import _DTYPE_BYTES, MetaGraph

from .findings import Finding, make_finding

# ops whose MetaIR node hides a sub-graph: not remat-chain material (the
# same exclusion as remat.py's _BANNED_PARAM_KEYS, in op_key vocabulary)
_COMPOSITE_OPS = frozenset((
    "scan", "while", "cond", "custom_jvp_call", "custom_vjp_call",
    "checkpoint", "remat", "remat2", "pjit", "closed_call",
))

# cap repeated-findings floods: each seeded fixture fires exactly once, and
# a systematically-broken plan does not drown the report
_MAX_PER_CHECK = 8


# ------------------------------------------------------ MEM001: lifetimes

def recompute_liveness(graph: MetaGraph
                       ) -> Dict[str, Tuple[int, int]]:
    """Producer/last-consumer intervals recomputed independently of
    `plan_graph_memory`: last uses come from a REVERSE operand scan over
    `node.invars` (the planner walks the forward `var.consumers` edge
    lists), so a corrupted edge list and a corrupted plan cannot agree by
    construction.  Graph outputs (op- or input-produced) are pinned live
    to the final op."""
    n_ops = len(graph.ops)
    out_names = {v.name for v in graph.outputs}
    last_use: Dict[str, int] = {}
    for i in range(n_ops - 1, -1, -1):
        for v in graph.ops[i].invars:
            if v is not None and v.name not in last_use:
                last_use[v.name] = i
    intervals: Dict[str, Tuple[int, int]] = {}
    for i, node in enumerate(graph.ops):
        for v in node.outvars:
            if v is None or v.name in intervals:
                continue
            end = max(i, last_use.get(v.name, i))
            if v.name in out_names:
                end = n_ops - 1
            intervals[v.name] = (i, end)
    for node in graph.inputs:
        for v in node.outvars:
            if v is None or v.name in intervals:
                continue
            end = last_use.get(v.name, 0)
            if v.name in out_names:
                end = n_ops - 1
            intervals[v.name] = (0, end)
    return intervals


def _vars_by_name(graph: MetaGraph) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for node in graph.ops + graph.inputs:
        for v in node.outvars:
            if v is not None and v.name not in out:
                out[v.name] = v
    return out


def _plan_placements(var, per_axis: Sequence[Dict]):
    """The placement slots `plan_graph_memory` sizes a var by (its
    producer's out placement per axis)."""
    node = var.producer
    out = []
    for chosen in per_axis:
        s = chosen.get(node.name) if node is not None else None
        if s is None or var.producer_idx >= len(s.out_placements):
            out.append(None)
        else:
            out.append(s.out_placements[var.producer_idx])
    return out


def _expected_sharded_bytes(var, per_axis, axis_sizes) -> int:
    """Independent re-derivation of the interval's per-device bytes:
    element-aligned, shard dims rounded up (ceil) per axis."""
    shape = list(var.shape)
    for p, n in zip(_plan_placements(var, per_axis), axis_sizes):
        if p is not None and p.is_shard() and n > 0 and p.dim < len(shape):
            shape[p.dim] = -(-int(shape[p.dim]) // int(n))
    elems = 1
    for d in shape:
        elems *= int(d)
    return max(elems * _DTYPE_BYTES.get(var.dtype, 4), 1)


def verify_memory_plan(graph: MetaGraph, plan, per_axis: Sequence[Dict],
                       axis_sizes: Sequence[int]) -> List[Finding]:
    """MEM001 + MEM002 + MEM003 over one (graph, MemoryPlan) pair."""
    findings: List[Finding] = []

    # ---- MEM001: interval audit
    expected = recompute_liveness(graph)
    plan_iv = {name: (int(plan.starts[i]), int(plan.ends[i]))
               for i, name in enumerate(plan.var_names)}
    missing = sorted(set(expected) - set(plan_iv))
    extra = sorted(set(plan_iv) - set(expected))
    if missing:
        findings.append(make_finding(
            "MEM001", "memory-plan",
            f"{len(missing)} graph var(s) have no plan interval: "
            f"{missing[:6]}{'...' if len(missing) > 6 else ''}"))
    if extra:
        findings.append(make_finding(
            "MEM001", "memory-plan",
            f"{len(extra)} plan interval(s) name no graph var: "
            f"{extra[:6]}{'...' if len(extra) > 6 else ''}"))
    n_drift = 0
    for name in plan_iv:
        if name not in expected or n_drift >= _MAX_PER_CHECK:
            continue
        if plan_iv[name] != expected[name]:
            n_drift += 1
            findings.append(make_finding(
                "MEM001", f"memory-plan/{name}",
                f"interval {plan_iv[name]} but the independent "
                f"producer/last-consumer audit gives {expected[name]}"))

    # ---- MEM002: sizing audit
    vars_by_name = _vars_by_name(graph)
    n_size = 0
    for i, name in enumerate(plan.var_names):
        v = vars_by_name.get(name)
        if v is None or n_size >= _MAX_PER_CHECK:
            continue
        want = _expected_sharded_bytes(v, per_axis, axis_sizes)
        got = int(plan.sizes[i])
        if got != want:
            n_size += 1
            findings.append(make_finding(
                "MEM002", f"memory-plan/{name}",
                f"interval sized {got} bytes but the placement-divided "
                f"size of {v!r} is {want} (shard dims rounded up to whole "
                f"elements)"))

    # ---- MEM003: skyline soundness
    for i, j in plan.validate()[:_MAX_PER_CHECK]:
        findings.append(make_finding(
            "MEM003", f"memory-plan/{plan.var_names[i]}",
            f"offset range overlaps {plan.var_names[j]} while both are "
            f"live (offsets {int(plan.offsets[i])}+{int(plan.sizes[i])} "
            f"vs {int(plan.offsets[j])}+{int(plan.sizes[j])})"))
    if plan.peak_bytes < plan.peak_live_bytes:
        findings.append(make_finding(
            "MEM003", "memory-plan/peak",
            f"skyline peak {plan.peak_bytes} below the sum-of-live lower "
            f"bound {plan.peak_live_bytes} — a packing cannot beat "
            f"simultaneous liveness"))
    if len(plan.sizes):
        extent = int(np.max(plan.offsets + plan.sizes))
        if plan.peak_bytes != extent:
            findings.append(make_finding(
                "MEM003", "memory-plan/peak",
                f"declared peak {plan.peak_bytes} != packed extent "
                f"{extent} (max offset+size)"))
    return findings


# ------------------------------------------------- MEM004: HBM budget gate

def resolve_hbm_budget(mesh=None) -> int:
    """Per-device HBM capacity the MEM004 gate verifies against.
    `edconfig.analyze_hbm_budget` wins when set (>0); 0 disables; the
    default (-1) asks the real device's memory_stats and falls back to the
    platform default (`hbm_capacity_default`, v5e 16 GiB) on backends that
    do not report one (CPU virtual meshes)."""
    from easydist_tpu import config as edconfig

    cap = edconfig.analyze_hbm_budget
    if cap >= 0:
        return int(cap)
    if mesh is not None:
        try:
            dev = np.asarray(mesh.devices).flat[0]
            stats = dev.memory_stats()
            if stats:
                limit = stats.get("bytes_limit") or stats.get(
                    "bytes_reservable_limit")
                if limit:
                    return int(limit)
        except Exception:
            pass
    return int(edconfig.hbm_capacity_default)


def _node_recompute_seconds(node) -> float:
    """FLOP-proxy recompute cost of re-executing one producer node —
    the same cost dimension remat.py prices chains in (exact bridge-
    recorded MACs when available, output elements otherwise, at
    `peak_flops`)."""
    from easydist_tpu import config as edconfig

    flops = node.flops
    if flops is None:
        flops = 0.0
        for v in node.outvars:
            if v is not None:
                n = 1
                for d in v.shape:
                    n *= int(d)
                flops += float(n)
    return float(flops) / max(edconfig.peak_flops, 1.0)


def remat_advisory(graph: MetaGraph, plan, budget: int,
                   predicted: Optional[int] = None,
                   max_names: int = 6) -> str:
    """Which vars, taken in `schedule/remat.py`'s largest-bytes-per-
    recompute-second order, would bring the predicted peak under `budget`.
    Candidates must span the peak step strictly (their eviction moves the
    peak) and have a flat, re-executable producer."""
    from easydist_tpu.schedule.remat import candidate_score

    predicted = plan.peak_bytes if predicted is None else int(predicted)
    overshoot = predicted - budget
    profile = native.live_profile(plan.starts, plan.ends, plan.sizes)
    if profile.size == 0:
        return "no live intervals to rematerialize"
    t_star = int(profile.argmax())
    vars_by_name = _vars_by_name(graph)
    cands: List[Tuple[float, str, int]] = []
    for i, name in enumerate(plan.var_names):
        if not (int(plan.starts[i]) < t_star < int(plan.ends[i])):
            continue
        v = vars_by_name.get(name)
        node = v.producer if v is not None else None
        if node is None or node.is_input or node.op_key in _COMPOSITE_OPS:
            continue
        nbytes = int(plan.sizes[i])
        cands.append((candidate_score(nbytes,
                                      _node_recompute_seconds(node)),
                      name, nbytes))
    cands.sort(key=lambda c: (-c[0], c[1]))
    picked, cum = [], 0
    for _, name, nbytes in cands:
        if cum >= overshoot:
            break
        picked.append(f"{name}({nbytes}B)")
        cum += nbytes
    if not picked:
        return (f"over budget by {overshoot} bytes with no "
                f"rematerializable candidate spanning peak step {t_star}")
    shown = ", ".join(picked[:max_names])
    if len(picked) > max_names:
        shown += f", ... +{len(picked) - max_names} more"
    verdict = ("sufficient to fit" if cum >= overshoot else
               f"covers only {cum} of the {overshoot}-byte overshoot")
    return (f"remat advisory (largest bytes-per-recompute-second first): "
            f"recompute {shown} — {verdict}")


def check_hbm_budget(graph: Optional[MetaGraph], plan, budget: int,
                     remat_plan=None) -> List[Finding]:
    """MEM004: the predicted per-device peak of the program that ships
    (the remat plan's post-rewrite peak when a rewrite was applied, the
    graph skyline otherwise) must fit `budget`."""
    if budget <= 0 or plan is None:
        return []
    predicted = (int(remat_plan.predicted_peak) if remat_plan
                 else int(plan.peak_bytes))
    if predicted <= budget:
        return []
    advisory = (remat_advisory(graph, plan, budget, predicted=predicted)
                if graph is not None else "no MetaGraph for an advisory")
    return [make_finding(
        "MEM004", "memory-plan/budget",
        f"predicted per-device peak {predicted} bytes "
        f"({predicted / 2**20:.2f} MiB) exceeds the HBM budget {budget} "
        f"bytes ({budget / 2**20:.2f} MiB); {advisory}")]


# -------------------------------------------------- MEM005: remat rewrite

def _jaxpr_contains(jaxpr, prim_name: str) -> bool:
    from .jaxpr_rules import _sub_jaxprs

    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == prim_name:
            return True
        for _, sub in _sub_jaxprs(eqn):
            if _jaxpr_contains(sub, prim_name):
                return True
    return False


def audit_remat_plan(closed_jaxpr, remat_plan,
                     traced=None) -> List[Finding]:
    """MEM005 over one (traced jaxpr, RematPlan) pair.  `traced` is the
    retraced EMITTED program (when available): it must carry the
    `optimization_barrier` reads that keep XLA CSE from folding the
    recomputed chains back into the originals."""
    from easydist_tpu.schedule.remat import _BANNED_PARAM_KEYS

    findings: List[Finding] = []
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    n = len(jaxpr.eqns)
    chain_eqns = sorted({e for ch in remat_plan.recompute.values()
                         for e in ch})
    n_flat = 0
    for e in chain_eqns:
        if not (0 <= e < n):
            findings.append(make_finding(
                "MEM005", f"remat/eqn{e}",
                f"chain equation index {e} outside the program "
                f"(0..{n - 1})"))
            continue
        eqn = jaxpr.eqns[e]
        bad = [k for k in _BANNED_PARAM_KEYS if k in eqn.params]
        if bad and n_flat < _MAX_PER_CHECK:
            n_flat += 1
            findings.append(make_finding(
                "MEM005", f"remat/eqn{e}:{eqn.primitive.name}",
                f"recompute chain re-executes non-flat primitive "
                f"{eqn.primitive.name!r} (carries sub-jaxpr params {bad}) "
                f"— chains must be pure flat equations"))
    for consumer in sorted(remat_plan.recompute):
        late = [e for e in remat_plan.recompute[consumer]
                if 0 <= e < n and e >= consumer]
        if late:
            findings.append(make_finding(
                "MEM005", f"remat/consumer{consumer}",
                f"chain equation(s) {late[:4]} do not precede their "
                f"consumer eqn {consumer} — not a topological recompute"))
    if remat_plan.recompute and \
            remat_plan.predicted_peak >= remat_plan.base_peak:
        findings.append(make_finding(
            "MEM005", "remat/peak",
            f"rewrite does not lower the planned peak "
            f"({remat_plan.base_peak} -> {remat_plan.predicted_peak} "
            f"bytes) — recompute cost with no memory win"))
    if traced is not None and remat_plan.recompute and \
            not _jaxpr_contains(traced, "optimization_barrier"):
        findings.append(make_finding(
            "MEM005", "remat/emission",
            "emitted program carries no optimization_barrier: XLA CSE "
            "can fold every recomputed chain back into the original "
            "values, silently undoing the rewrite"))
    return findings
