"""Layer 1: static verifier over solved MetaIR strategies (one mesh axis).

Runs after `SpmdSolver.solve` and before emission, on exactly the
(MetaGraph, chosen strategies) pair the solver produced for that axis.  The
invariants are the redistribution-typing rules of arXiv:2112.01075 applied
to our placement vocabulary:

  * R -> R/S and S -> R/S/S' edges are priced reshards (local slice,
    all_gather, all_to_all) — always realizable;
  * nothing materializes a PARTIAL from whole values: an edge whose
    consumer expects P while the producer emits R/S has no collective
    realization (STRAT001);
  * S(dim) must address a real tensor dim and divide it by the axis size,
    or the emitted PartitionSpec is meaningless (STRAT002);
  * a PARTIAL is resolved by a matching reduction (all_reduce /
    reduce_scatter at a priced edge or a region fence) before any
    non-linear consumer, never rides both operands of a bilinear op or a
    divisor, never changes reduction kind mid-flight, and never escapes at
    a graph output (STRAT003/STRAT004);
  * the solver's reported edge-communication objective must match an
    independent recomputation through `assignment_comm_cost` — a drift
    means the pick -> strategy-table mapping is corrupted (STRAT005).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from easydist_tpu.metashard.metair import MetaGraph, NodeStrategy

from .findings import Finding, make_finding

# ops through which a pending PARTIAL propagates linearly:
# f(sum_i x_i) == sum_i f(x_i) when every other operand is replicated.
# Union of the pool-injection sets (interpreter._PARTIAL_LINEAR_*), the
# region chain ops (partial_regions._REGION_PRIMS), and additive combiners.
_P_LINEAR_OPS = frozenset((
    "reshape", "transpose", "convert_element_type", "squeeze",
    "expand_dims", "broadcast_in_dim", "neg", "rev", "slice", "copy",
    "reduce_sum", "mul", "div", "dot_general", "add", "sub", "add_any",
    "concatenate", "pad",
    # composites carry explicit strategies validated by their own inner
    # solves; a P at their boundary is a vetted reduce-recombine
    "scan", "while", "cond",
))

# bilinear in their operand pair: P may ride exactly ONE side
_P_BILINEAR_OPS = frozenset(("mul", "dot_general"))

# objective audit tolerance: reported and recomputed costs walk the same
# float32-ish matrices, so anything beyond rounding noise is a real drift
_AUDIT_RTOL = 1e-6
_AUDIT_ATOL = 1e-9


def _placement_str(p) -> str:
    return "None" if p is None else repr(p)


def _node_loc(node) -> str:
    return f"{node.name}({node.op_key})"


def verify_axis(graph: MetaGraph, chosen: Dict[str, NodeStrategy],
                axis) -> List[Finding]:
    """Check one axis's solved strategy assignment.  `axis` needs `.name`
    and `.size` (a MeshAxisSpec).  Returns findings (empty = clean)."""
    findings: List[Finding] = []
    ax = f"axis {axis.name}"

    # ---- STRAT002: S(dim) rank / divisibility, every placement slot
    for node in graph.all_nodes():
        s = chosen.get(node.name)
        if s is None:
            continue
        slots = []
        if not node.is_input:
            slots.extend(zip(node.invars, s.in_placements))
        slots.extend(zip(node.outvars, s.out_placements))
        seen_var_slots = set()
        for v, p in slots:
            if v is None or p is None or not p.is_shard():
                continue
            key = (id(v), p.dim)
            if key in seen_var_slots:
                continue  # one finding per (var, dim), not per slot
            seen_var_slots.add(key)
            if p.dim < 0 or p.dim >= len(v.shape):
                findings.append(make_finding(
                    "STRAT002", f"{_node_loc(node)}/{v.name}",
                    f"{ax}: S({p.dim}) addresses dim {p.dim} of rank-"
                    f"{len(v.shape)} tensor {v.name}{list(v.shape)}"))
            elif axis.size > 0 and v.shape[p.dim] % axis.size != 0:
                findings.append(make_finding(
                    "STRAT002", f"{_node_loc(node)}/{v.name}",
                    f"{ax}: S({p.dim}) shards dim of size "
                    f"{v.shape[p.dim]} across {axis.size} devices "
                    f"(not divisible)"))

    # ---- edge rules: STRAT001 (unrealizable P edge) + STRAT004 (reduction
    # mismatch, P into non-linear consumer, P on both bilinear operands)
    for node in graph.ops:
        s = chosen.get(node.name)
        if s is None:
            continue
        n_p_in = 0
        for in_idx, v in enumerate(node.invars):
            if v is None or in_idx >= len(s.in_placements):
                continue
            dn_p = s.in_placements[in_idx]
            if dn_p is None or not dn_p.is_partial():
                continue
            n_p_in += 1
            loc = f"{_node_loc(node)}/in{in_idx}"
            if node.op_key not in _P_LINEAR_OPS:
                findings.append(make_finding(
                    "STRAT004", loc,
                    f"{ax}: PARTIAL({dn_p.reduction.value}) rides into "
                    f"non-linear op {node.op_key!r} without a reduction "
                    f"fence"))
            if node.op_key == "div" and in_idx == 1:
                findings.append(make_finding(
                    "STRAT004", loc,
                    f"{ax}: PARTIAL in the divisor of a div (linear in "
                    f"the numerator only)"))
            up = v.producer
            if up is None:
                continue
            up_s = chosen.get(up.name)
            if up_s is None or v.producer_idx >= len(up_s.out_placements):
                continue
            up_p = up_s.out_placements[v.producer_idx]
            if up_p is None or not up_p.is_partial():
                findings.append(make_finding(
                    "STRAT001", loc,
                    f"{ax}: consumer expects "
                    f"{_placement_str(dn_p)} but producer "
                    f"{_node_loc(up)} emits {_placement_str(up_p)} on "
                    f"{v.name} — no collective materializes a partial "
                    f"from whole values"))
            elif up_p.reduction != dn_p.reduction:
                findings.append(make_finding(
                    "STRAT004", loc,
                    f"{ax}: reduction mismatch on {v.name}: producer "
                    f"P({up_p.reduction.value}) vs consumer "
                    f"P({dn_p.reduction.value})"))
        if n_p_in >= 2 and node.op_key in _P_BILINEAR_OPS:
            findings.append(make_finding(
                "STRAT004", _node_loc(node),
                f"{ax}: PARTIAL on {n_p_in} operands of bilinear op "
                f"{node.op_key!r} (sum-of-products != product-of-sums)"))

    # ---- STRAT003: P never escapes at graph outputs.  Non-state outputs
    # are handed back replicated and state outputs thread into next-step
    # placeholders (whose pools are R/S only); either way a PARTIAL here is
    # an unreduced value crossing the program boundary.
    for v in graph.outputs:
        if v.producer is None:
            continue
        s = chosen.get(v.producer.name)
        if s is None or v.producer_idx >= len(s.out_placements):
            continue
        p = s.out_placements[v.producer_idx]
        if p is not None and p.is_partial():
            kind = "state" if v.name in graph.state_io else "plain"
            findings.append(make_finding(
                "STRAT003", f"output/{v.name}",
                f"{ax}: {kind} graph output {v.name} carries "
                f"P({p.reduction.value}) — pending reduction escapes the "
                f"program"))
    return findings


def audit_solver_objective(solver, chosen: Dict[str, NodeStrategy]
                           ) -> Tuple[Optional[Finding], Dict[str, float]]:
    """STRAT005: recompute the chosen assignment's edge-communication cost
    through `assignment_comm_cost` (which independently re-derives each
    cluster's pick by matching node strategies) and compare against the
    cost the solver reported from its own pick indices.

    Returns (finding or None, audit record).  The record is kept either
    way so clean runs carry affirmative evidence of the match."""
    reported = getattr(solver, "last_comm_cost", None)
    record: Dict[str, float] = {"axis": solver.axis.name}
    if reported is None:
        # beam/native path that predates the attribute, or no solve ran
        return None, record
    recomputed = solver.assignment_comm_cost(chosen)
    record["reported"] = float(reported)
    record["recomputed"] = float(recomputed)
    tol = _AUDIT_RTOL * max(abs(reported), abs(recomputed), 1.0) + _AUDIT_ATOL
    if not math.isfinite(recomputed) or abs(recomputed - reported) > tol:
        return make_finding(
            "STRAT005", f"solver/{solver.axis.name}",
            f"axis {solver.axis.name}: solver reported edge-comm cost "
            f"{reported:.6e} but independent recomputation gives "
            f"{recomputed:.6e} (tolerance {tol:.1e}) — strategy table and "
            f"solution picks disagree"), record
    return None, record
