"""Layer 2: collective-program linter over emitted jaxprs and comm plans.

Works on the program that actually ships: the jaxpr traced from the
emitted sharded function (partial-region `shard_map` fences, comm-layer
collectives, dp/zero/pipeline `shard_map` programs all appear here), plus
the bucketer's packing plans.  Rules:

  COLL001  every collective's named axis exists in the mesh;
  COLL002  `cond`/`switch` branches carry identical collective programs —
           a branch-dependent collective is the classic SPMD deadlock
           shape (devices disagreeing on the predicate post different
           collectives and hang);
  COLL003  a bucket plan's slices tile the flat buffer exactly: every
           leaf in exactly one bucket, no overlap/gap, byte counts
           consistent, dtypes uniform per bucket;
  COLL004  arithmetic reduction collectives (psum/pmin/pmax/
           reduce_scatter) never see an int8/uint8 operand — the
           quantized scheme sums in f32 after dequantize (two-pass
           scale); int8 accumulation on the wire overflows at axis
           sizes as small as 2;
  COLL005  collectives inside a `while` predicate get a warning: if the
           predicate diverges across devices the trip counts diverge and
           the program deadlocks.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

from .findings import Finding, make_finding

# primitives that perform arithmetic on the wire (int8 operands overflow);
# pmean lowers to psum + div so it is covered by "psum"
_REDUCING_COLLECTIVES = frozenset((
    "psum", "pmin", "pmax", "reduce_scatter", "psum_scatter",
))
# primitives that carry data without reducing (safe for int8 payloads —
# this is exactly why the two-pass quantized scheme is clean)
_MOVING_COLLECTIVES = frozenset((
    "all_gather", "all_to_all", "ppermute", "pbroadcast", "axis_index",
))
_COLLECTIVES = _REDUCING_COLLECTIVES | _MOVING_COLLECTIVES

_INT8_DTYPES = ("int8", "uint8")


def _axis_names(eqn) -> List[str]:
    """Named mesh axes a collective eqn binds (positional int axes from
    vmap are not mesh axes and are skipped)."""
    names: List[str] = []
    for key in ("axes", "axis_name"):
        if key not in eqn.params:
            continue
        val = eqn.params[key]
        vals = val if isinstance(val, (tuple, list)) else (val,)
        names.extend(v for v in vals if isinstance(v, str))
    return names


def _sub_jaxprs(eqn) -> List[Tuple[str, object]]:
    """(param_key, jaxpr) for every sub-jaxpr in an eqn's params, in a
    stable order.  Handles pjit (`jaxpr`), scan/while/cond (`jaxpr`,
    `cond_jaxpr`, `body_jaxpr`, `branches`), shard_map, custom_* calls."""
    out: List[Tuple[str, object]] = []
    for key in sorted(eqn.params):
        val = eqn.params[key]
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for i, v in enumerate(vals):
            inner = getattr(v, "jaxpr", v)
            if hasattr(inner, "eqns"):
                label = f"{key}{i}" if isinstance(val, (tuple, list)) else key
                out.append((label, inner))
    return out


def _collective_signature(jaxpr) -> List[Tuple[str, Tuple[str, ...]]]:
    """Ordered (primitive, named axes) of every collective in `jaxpr`,
    recursively — the "shape" that must agree across cond branches for the
    program to be deadlock-free."""
    sig: List[Tuple[str, Tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _COLLECTIVES:
            sig.append((name, tuple(_axis_names(eqn))))
        for _, sub in _sub_jaxprs(eqn):
            sig.extend(_collective_signature(sub))
    return sig


def lint_jaxpr(jaxpr, axis_sizes: Dict[str, int],
               _path: str = "") -> List[Finding]:
    """Lint one jaxpr (recursively) against a mesh given as
    {axis_name: size}.  Accepts a Jaxpr or ClosedJaxpr."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    findings: List[Finding] = []
    for idx, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        loc = f"{_path}eqn{idx}:{name}"

        if name in _COLLECTIVES:
            for ax in _axis_names(eqn):
                if ax not in axis_sizes:
                    findings.append(make_finding(
                        "COLL001", loc,
                        f"collective {name!r} names mesh axis {ax!r}; "
                        f"mesh has {sorted(axis_sizes)}"))
            if name in _REDUCING_COLLECTIVES:
                bad = [v for v in eqn.invars
                       if hasattr(v, "aval")
                       and str(getattr(v.aval, "dtype", "")) in _INT8_DTYPES]
                if bad:
                    findings.append(make_finding(
                        "COLL004", loc,
                        f"{name!r} accumulates {len(bad)} int8-typed "
                        f"operand(s) on the wire — quantized reductions "
                        f"must dequantize to f32 before summing "
                        f"(two-pass scale)"))

        if name == "cond":
            branches = eqn.params.get("branches", ())
            sigs = [_collective_signature(getattr(b, "jaxpr", b))
                    for b in branches]
            if len({tuple(s) for s in sigs}) > 1:
                detail = "; ".join(
                    f"branch{i}={s or 'none'}" for i, s in enumerate(sigs))
                findings.append(make_finding(
                    "COLL002", loc,
                    f"cond branches disagree on collective programs "
                    f"({detail}) — devices taking different branches "
                    f"deadlock"))

        if name == "while":
            cond_j = eqn.params.get("cond_jaxpr")
            if cond_j is not None:
                csig = _collective_signature(getattr(cond_j, "jaxpr", cond_j))
                if csig:
                    findings.append(make_finding(
                        "COLL005", loc,
                        f"while predicate contains collectives {csig}: "
                        f"safe only if the predicate is replicated "
                        f"(divergent trip counts deadlock)"))

        for label, sub in _sub_jaxprs(eqn):
            findings.extend(lint_jaxpr(sub, axis_sizes,
                                       _path=f"{loc}/{label}/"))
    return findings


def lint_fn(fn, *example_args, axis_sizes: Dict[str, int],
            **example_kwargs) -> List[Finding]:
    """Trace `fn` with jax.make_jaxpr and lint the result — the
    entry point for the dp/zero/pipeline paths, whose programs only exist
    as traceable callables."""
    import jax

    closed = jax.make_jaxpr(fn)(*example_args, **example_kwargs)
    return lint_jaxpr(closed.jaxpr, axis_sizes)


# --------------------------------------------------------------- bucket lint

def lint_bucket_plan(leaves: Sequence, buckets: Iterable) -> List[Finding]:
    """COLL003: verify a `comm.bucketer` plan tiles the flat leaf set
    exactly.  `leaves` are the arrays handed to `plan_buckets`; `buckets`
    the resulting plan.  Checks (one finding per violation kind/site):

      * every leaf index in range and in exactly one bucket (a duplicate
        is an overlapping slice; missing indices are a gap);
      * each bucket's `nbytes` equals the sum of its leaves' bytes (an
        off-by-one slice shows up here);
      * one dtype per bucket (pack/unpack are cast-free by contract).
    """
    findings: List[Finding] = []
    seen: Dict[int, int] = {}
    for b_idx, b in enumerate(buckets):
        loc = f"bucket{b_idx}"
        nbytes = 0
        dtypes = set()
        for i in b.indices:
            if i < 0 or i >= len(leaves):
                findings.append(make_finding(
                    "COLL003", loc,
                    f"leaf index {i} out of range (have {len(leaves)} "
                    f"leaves)"))
                continue
            if i in seen:
                findings.append(make_finding(
                    "COLL003", loc,
                    f"leaf {i} already packed by bucket{seen[i]} — "
                    f"overlapping slices"))
            else:
                seen[i] = b_idx
            leaf = leaves[i]
            nbytes += leaf.size * leaf.dtype.itemsize
            dtypes.add(str(leaf.dtype))
        if len(dtypes) > 1:
            findings.append(make_finding(
                "COLL003", loc,
                f"mixed dtypes {sorted(dtypes)} in one bucket (packing "
                f"must be cast-free)"))
        if nbytes != b.nbytes:
            findings.append(make_finding(
                "COLL003", loc,
                f"bucket claims {b.nbytes} bytes but its leaves hold "
                f"{nbytes} — slice offsets will not tile the flat buffer"))
    missing = [i for i in range(len(leaves)) if i not in seen]
    if missing:
        findings.append(make_finding(
            "COLL003", "plan",
            f"{len(missing)} leaf/leaves never packed (gap): indices "
            f"{missing[:8]}{'...' if len(missing) > 8 else ''}"))
    return findings
