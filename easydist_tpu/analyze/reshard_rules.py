"""Layer 8: redistribution auditor — chunked-plan byte bounds and
restored-sharding agreement (`easydist_tpu.reshard`).

The reshard substrate's whole contract is "never the global array":
every plan promises peak live bytes of O(max(src_shard, dst_shard) +
chunk).  These rules make that promise checkable BEFORE bytes move and
verifiable AFTER a restore lands:

  RESHARD001 (error)  a plan's `peak_live_bytes()` exceeds its
                      `chunked_bound()`.  The usual causes: a chunk
                      ceiling silently ignored (one ChunkOp staging the
                      whole array), or a planner change that regressed
                      to replicate-then-slice.  Peak bytes at real model
                      scale IS the OOM that kills an elastic restart.
  RESHARD002 (error)  a restored leaf's sharding disagrees with the
                      restore template's spec.  The caller's jit owns
                      the layout; a leaf that came back replicated (or
                      on the wrong axis) costs n_devices x its byte
                      budget and a re-layout collective on every step —
                      bitwise-invisible, so only an audit catches it.

Both audit plain data (a `ReshardPlan`, a pair of pytrees), so goldens
are cheap fixtures, not compiled programs.
"""

from __future__ import annotations

from typing import Any, List

from .findings import Finding, make_finding

__all__ = ["audit_reshard_plan", "audit_restored_state"]


def audit_reshard_plan(plan, node: str = "reshard") -> List[Finding]:
    """RESHARD001 over one redistribution plan (reshard.plan.ReshardPlan
    or anything exposing peak_live_bytes()/chunked_bound())."""
    findings: List[Finding] = []
    peak = int(plan.peak_live_bytes())
    bound = int(plan.chunked_bound())
    if peak > bound:
        n_chunks = len(getattr(plan, "chunks", ()) or ())
        findings.append(make_finding(
            "RESHARD001", node,
            f"plan peak live bytes {peak} exceed the chunked bound "
            f"{bound} (src_shard={getattr(plan, 'src_shard_bytes', '?')}, "
            f"dst_shard={getattr(plan, 'dst_shard_bytes', '?')}, "
            f"chunk_limit={getattr(plan, 'chunk_limit_bytes', '?')}, "
            f"{n_chunks} chunk(s)) — the plan degenerated toward global "
            f"materialization"))
    return findings


def _sharding_equal(got, want, ndim: int) -> bool:
    if got is None or want is None:
        return got is want
    eq = getattr(want, "is_equivalent_to", None)
    if eq is not None:
        try:
            return bool(eq(got, ndim))
        except Exception:
            pass
    return got == want


def audit_restored_state(restored: Any, template: Any,
                         node: str = "restore") -> List[Finding]:
    """RESHARD002: every leaf whose template carried an explicit
    multi-device sharding must have come back on exactly that sharding.
    Template leaves without one (host arrays, ShapeDtypeStructs with no
    sharding) are unconstrained — the restore planner chose for them."""
    import jax

    findings: List[Finding] = []
    got_leaves, got_def = jax.tree_util.tree_flatten(restored)
    want_leaves, want_def = jax.tree_util.tree_flatten(template)
    if got_def != want_def:
        findings.append(make_finding(
            "RESHARD002", node,
            f"restored tree structure {got_def} differs from the "
            f"template {want_def}"))
        return findings
    for i, (got, want) in enumerate(zip(got_leaves, want_leaves)):
        want_sh = getattr(want, "sharding", None)
        if want_sh is None or getattr(want_sh, "num_devices", 1) <= 1:
            continue
        got_sh = getattr(got, "sharding", None)
        ndim = len(getattr(want, "shape", ()) or ())
        if not _sharding_equal(got_sh, want_sh, ndim):
            findings.append(make_finding(
                "RESHARD002", f"{node}.leaf[{i}]",
                f"restored sharding {got_sh} disagrees with the template "
                f"spec {want_sh} — the leaf will be re-laid-out (or held "
                f"replicated) on every step"))
    return findings
