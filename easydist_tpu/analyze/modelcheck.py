"""Analyze layer 12a: small-scope explicit-state model checker for the
fleet protocols (DistIR-style, arXiv:2111.05426 — replace "run the chaos
drill and hope" with exhaustive replay over an explicit model).

Each protocol is a `Spec`: a deterministic transition system of states
(canonical hashable tuples), guarded actions, a safety `invariant`, and
a `is_goal` predicate.  `explore()` runs BFS over ALL interleavings at
small scope (>=2 replicas x >=2 in-flight requests, crash / duplicate /
reorder / stall actions drawn from the fault catalog) with canonical
state hashing and a committed state-count budget — no wall clock, no
randomness, so the state counts in `COMMITTED_STATES` are reproducible
bit-for-bit and CI fails loudly when the explored space drifts >20%
from the committed budget (coverage silently shrinking is itself a bug).

Violations surface as findings:

- PROTO001 (safety): a reachable state violates the invariant — a
  dropped admitted request, a token position committed twice, a corrupt
  chunk accepted.  The shortest counterexample interleaving is attached
  (BFS discovery order IS shortest-trace order).
- PROTO002 (stuck): a reachable state has no path to the goal — either
  no enabled action, or a livelock cycle.  Detected by a reverse
  reachability pass from the goal set; only meaningful when the
  exploration was exhaustive.

The four shipped specs mirror `fleet/health.py`, `fleet/router.py`,
`fleet/failover.py`, and `fleet/transport.py`; each takes a `bug=`
seed that re-introduces a representative defect (flap-storm false DEAD,
dropped handoff, stale resume watermark, non-idempotent chunk commit)
so the goldens prove the checker actually catches what it claims to.

Layer 12b is the conformance bridge (PROTO003): the live classes expose
`transitions()` event streams and the `replay_*` validators below check
every observed drill transition against what the spec admits — the
model checker is a *checked* abstraction, not parallel documentation.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from easydist_tpu.analyze.findings import Finding, make_finding

__all__ = [
    "Spec", "ExplorationResult", "explore", "audit_spec",
    "HealthSpec", "RouterSpec", "ResumeSpec", "TransportSpec",
    "ALL_SPECS", "COMMITTED_STATES", "BUDGET_DRIFT_FRAC",
    "replay_health_events", "replay_router_protocol",
    "replay_transport_commits", "replay_restore_attempts",
]

State = Tuple
Action = Tuple[str, State]  # (action name, successor state)

# Committed exhaustive state counts per spec at the shipped scope.
# tests/test_analyze/test_modelcheck.py asserts EXACT equality and
# scripts/static_checks.sh fails on >BUDGET_DRIFT_FRAC drift — a spec
# edit that shrinks (or explodes) the explored space must re-commit its
# budget consciously, never silently.
COMMITTED_STATES: Dict[str, int] = {
    "health": 40,
    "router": 1048,
    "resume": 48,
    "transport": 552,
}
BUDGET_DRIFT_FRAC = 0.20

# exploration ceiling: comfortably above every committed budget, small
# enough that a runaway spec edit fails fast instead of eating CI
MAX_STATES_DEFAULT = 200_000


class Spec:
    """A deterministic protocol transition system.

    Subclasses define `initial_states()`, `enabled(state)` (guarded
    actions as `(name, successor)` pairs), `invariant(state)` (safety —
    a list of violation messages, empty when safe), and `is_goal(state)`
    (the quiescent "every request accounted for" predicate reverse
    reachability targets).  States must be canonical hashable tuples:
    two interleavings reaching the same protocol configuration MUST
    produce equal tuples, or the explorer double-counts."""

    name = "spec"

    def initial_states(self) -> Iterable[State]:
        raise NotImplementedError

    def enabled(self, state: State) -> List[Action]:
        raise NotImplementedError

    def invariant(self, state: State) -> List[str]:
        return []

    def is_goal(self, state: State) -> bool:
        raise NotImplementedError


@dataclass
class ExplorationResult:
    spec_name: str
    states: int
    transitions: int
    exhausted: bool
    # (trace of action names, violation messages) — at most one each,
    # the shortest counterexample, so seeded goldens fire exactly once
    safety: Optional[Tuple[Tuple[str, ...], Tuple[str, ...]]] = None
    stuck: Optional[Tuple[Tuple[str, ...], str]] = None
    goal_states: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "spec": self.spec_name,
            "states": self.states,
            "transitions": self.transitions,
            "exhausted": self.exhausted,
            "goal_states": self.goal_states,
            "committed": COMMITTED_STATES.get(self.spec_name),
            "safety_violation": (None if self.safety is None
                                 else list(self.safety[0])),
            "stuck_state": (None if self.stuck is None
                            else list(self.stuck[0])),
        }


def _trace(preds: Dict[State, Optional[Tuple[State, str]]],
           state: State) -> Tuple[str, ...]:
    """Action-name path from an initial state to `state` (shortest, by
    BFS construction)."""
    names: List[str] = []
    cur: Optional[State] = state
    while cur is not None:
        entry = preds[cur]
        if entry is None:
            break
        cur, act = entry
        names.append(act)
    return tuple(reversed(names))


def explore(spec: Spec,
            max_states: int = MAX_STATES_DEFAULT) -> ExplorationResult:
    """Exhaustive BFS over all interleavings of `spec` up to
    `max_states` distinct states.  Deterministic: action successors are
    sorted by (name, repr(state)), the queue is FIFO, and nothing reads
    a clock or an RNG — the same spec always yields the same counts,
    which is what lets `COMMITTED_STATES` be a committed contract.

    Safety-violating states are recorded and NOT expanded (everything
    past a violation is already broken).  The stuck check is a reverse
    BFS from the goal set over recorded edges; it only runs when the
    exploration was exhaustive (a truncated frontier would look stuck)."""
    preds: Dict[State, Optional[Tuple[State, str]]] = {}
    order: List[State] = []
    queue: deque = deque()
    for s in spec.initial_states():
        if s not in preds:
            preds[s] = None
            order.append(s)
            queue.append(s)
    edges: Dict[State, List[Action]] = {}
    bad: List[State] = []
    n_transitions = 0
    exhausted = True
    while queue:
        s = queue.popleft()
        if spec.invariant(s):
            bad.append(s)
            continue
        succs = sorted(spec.enabled(s), key=lambda a: (a[0], repr(a[1])))
        edges[s] = succs
        n_transitions += len(succs)
        for act, ns in succs:
            if ns in preds:
                continue
            if len(preds) >= max_states:
                exhausted = False
                continue
            preds[ns] = (s, act)
            order.append(ns)
            queue.append(ns)

    result = ExplorationResult(spec_name=spec.name, states=len(preds),
                               transitions=n_transitions,
                               exhausted=exhausted)
    bad_set = set(bad)
    if bad:
        # order[] is BFS order, so the first violating state found when
        # scanning discovery order has the shortest trace
        first = next(s for s in order if s in bad_set)
        result.safety = (_trace(preds, first),
                         tuple(spec.invariant(first)))

    goals = [s for s in order if s not in bad_set and spec.is_goal(s)]
    result.goal_states = len(goals)
    if exhausted and not bad:
        # reverse reachability: which states can still reach a goal?
        rev: Dict[State, List[State]] = {}
        for s, succs in edges.items():
            for _act, ns in succs:
                rev.setdefault(ns, []).append(s)
        can_reach = set(goals)
        rq: deque = deque(goals)
        while rq:
            s = rq.popleft()
            for p in rev.get(s, ()):
                if p not in can_reach:
                    can_reach.add(p)
                    rq.append(p)
        for s in order:  # BFS order -> shortest stuck trace
            if s not in can_reach:
                kind = ("no enabled action" if not edges.get(s)
                        else "livelock: goal unreachable")
                result.stuck = (_trace(preds, s), kind)
                break
    return result


def audit_spec(spec: Spec, node: Optional[str] = None,
               max_states: int = MAX_STATES_DEFAULT,
               ) -> Tuple[List[Finding], ExplorationResult]:
    """Explore `spec` and convert violations to findings: at most one
    PROTO001 (shortest safety counterexample) and one PROTO002
    (shortest stuck state) per spec."""
    node = node or f"protocol:{spec.name}"
    res = explore(spec, max_states=max_states)
    findings: List[Finding] = []
    if res.safety is not None:
        trace, msgs = res.safety
        findings.append(make_finding(
            "PROTO001", node,
            f"safety violated after [{' -> '.join(trace)}]: "
            f"{'; '.join(msgs)}"))
    if res.stuck is not None:
        trace, kind = res.stuck
        findings.append(make_finding(
            "PROTO002", node,
            f"stuck state ({kind}) reached via "
            f"[{' -> '.join(trace)}]: goal unreachable"))
    return findings, res


# ===================================================================
# Spec 1: HealthMonitor — fleet/health.py
# ===================================================================

class HealthSpec(Spec):
    """ALIVE/SUSPECT/DEAD per replica under honest probes, wedges,
    `fleet.probe.flap` false misses, and revives.

    State: (per-replica (truth, mon, misses) ..., flaps_used) where
    truth in {h(ealthy), w(edged)} and mon in {a, s, d}.

    Safety (PROTO001): no false DEAD — mon == DEAD implies the replica
    truly wedged inside the liveness window.  Holds because the flap
    budget (`fleet.probe.flap` fires once per plan occurrence) is
    strictly below the miss budget, the same contract health.py's
    docstring commits to.  `bug="flap_storm"` lifts the flap budget to
    the miss budget and the false-DEAD counterexample appears.

    Liveness (PROTO002): SUSPECT always resolves — every reachable
    state can reach "all replicas ALIVE-or-DEAD" (goal reachability
    covers both terminal resolution and the revive path)."""

    name = "health"

    def __init__(self, n_replicas: int = 2, miss_budget: int = 2,
                 bug: Optional[str] = None):
        self.n = n_replicas
        self.miss_budget = miss_budget
        # one false miss is absorbable; miss_budget of them is the bug
        self.max_flaps = miss_budget if bug == "flap_storm" else 1
        self.bug = bug

    def initial_states(self):
        yield tuple(("h", "a", 0) for _ in range(self.n)) + (0,)

    def enabled(self, state):
        reps, flaps = state[:-1], state[-1]
        out: List[Action] = []

        def with_rep(i, rep, df=0):
            new = list(reps)
            new[i] = rep
            return tuple(new) + (flaps + df,)

        for i, (truth, mon, misses) in enumerate(reps):
            if truth == "h" and mon != "d":
                out.append((f"wedge[{i}]", with_rep(i, ("w", mon, misses))))
            if mon != "d":
                # honest probe: progress iff truly healthy
                if truth == "h":
                    out.append((f"probe[{i}]", with_rep(i, ("h", "a", 0))))
                else:
                    m = misses + 1
                    nm = "d" if m >= self.miss_budget else "s"
                    out.append((f"probe[{i}]",
                                with_rep(i, (truth, nm, m))))
                # fleet.probe.flap: the probe lies about progress once
                if truth == "h" and flaps < self.max_flaps:
                    m = misses + 1
                    nm = "d" if m >= self.miss_budget else "s"
                    out.append((f"probe_flap[{i}]",
                                with_rep(i, ("h", nm, m), df=1)))
            if mon == "d":
                # add_replica under the old id: fresh session, revive()
                out.append((f"revive[{i}]", with_rep(i, ("h", "a", 0))))
        return out

    def invariant(self, state):
        msgs = []
        for i, (truth, mon, _misses) in enumerate(state[:-1]):
            if mon == "d" and truth == "h":
                msgs.append(f"replica {i} declared DEAD while healthy "
                            f"(false positive inside the liveness window)")
        return msgs

    def is_goal(self, state):
        return all(mon in ("a", "d") for _t, mon, _m in state[:-1])


# ===================================================================
# Spec 2: FleetRouter drain + handoff — fleet/router.py
# ===================================================================

_Q_TERMINAL = ("done", "failed", "quarantined")


class RouterSpec(Spec):
    """Zero-drop routing: every admitted request is completed exactly
    once on some replica — or fails/quarantines LOUDLY — under any
    interleaving of crashes, drains, evacuations, disaggregated
    prefill handoffs, and revives.

    State: (replica statuses, per-request (phase, n_crashes, n_done)).
    Phases: pending | ("prefill", p, d) | ("running", r) | done |
    failed | quarantined | lost (bug only).

    Safety (PROTO001): n_done <= 1 always, and phase == done implies
    n_done == 1 (completed-exactly-once).
    Stuck (PROTO002): a request stranded where no action can retire it.
    `bug="dropped_handoff"` makes a prefill-replica crash silently drop
    the in-flight handoff instead of resubmitting — the stranded `lost`
    phase is exactly the stuck state the checker reports."""

    name = "router"

    def __init__(self, n_replicas: int = 2, n_requests: int = 2,
                 quarantine_after: int = 2, bug: Optional[str] = None):
        self.n = n_replicas
        self.m = n_requests
        self.quarantine_after = quarantine_after
        self.bug = bug

    def initial_states(self):
        yield (tuple("up" for _ in range(self.n)),
               tuple(("pending", 0, 0) for _ in range(self.m)))

    def enabled(self, state):
        status, reqs = state
        out: List[Action] = []

        def with_status(r, st, reqs2=None):
            s2 = list(status)
            s2[r] = st
            return (tuple(s2), reqs if reqs2 is None else tuple(reqs2))

        def with_req(q, req):
            r2 = list(reqs)
            r2[q] = req
            return (status, tuple(r2))

        any_up = any(s == "up" for s in status)

        # ---- replica actions
        for r, st in enumerate(status):
            if st != "crashed":
                # fleet.replica.crash: every in-flight request on r is
                # recovered from its ResumeDescriptor (or quarantined
                # past the crash budget); prefill handoffs involving r
                # are resubmitted — unless the seeded bug drops them
                reqs2 = []
                for phase, nc, nd in reqs:
                    if phase == ("running", r):
                        nc += 1
                        phase = ("quarantined"
                                 if nc >= self.quarantine_after
                                 else "pending")
                    elif (isinstance(phase, tuple) and phase[0] == "prefill"
                          and r in phase[1:]):
                        if self.bug == "dropped_handoff" and phase[1] == r:
                            phase = "lost"   # handoff vanishes silently
                        else:
                            nc += 1
                            phase = ("quarantined"
                                     if nc >= self.quarantine_after
                                     else "pending")
                    reqs2.append((phase, nc, nd))
                out.append((f"crash[{r}]",
                            with_status(r, "crashed", reqs2)))
            if st == "up" and any(s == "up" for i, s in enumerate(status)
                                  if i != r):
                # the autoscaler never drains the last live replica
                out.append((f"drain[{r}]", with_status(r, "draining")))
            if st == "crashed":
                out.append((f"revive[{r}]", with_status(r, "up")))
            if st == "draining" and not any(
                    phase == ("running", r)
                    or (isinstance(phase, tuple) and phase[0] == "prefill"
                        and r in phase[1:])
                    for phase, _nc, _nd in reqs):
                # drain complete: the empty replica leaves the fleet
                out.append((f"drain_done[{r}]",
                            with_status(r, "crashed")))

        # ---- request actions
        for q, (phase, nc, nd) in enumerate(reqs):
            if phase == "pending":
                for r, st in enumerate(status):
                    if st == "up":
                        out.append((f"route[{q}->{r}]",
                                    with_req(q, (("running", r), nc, nd))))
                        for d, std in enumerate(status):
                            if d != r and std == "up":
                                out.append(
                                    (f"route_disagg[{q}:{r}->{d}]",
                                     with_req(q, (("prefill", r, d),
                                                  nc, nd))))
                if not any_up:
                    # admission failure is loud, never a silent drop
                    out.append((f"fail[{q}]",
                                with_req(q, ("failed", nc, nd))))
            elif isinstance(phase, tuple) and phase[0] == "prefill":
                _tag, p, d = phase
                if status[d] != "crashed":
                    out.append((f"handoff_commit[{q}]",
                                with_req(q, (("running", d), nc, nd))))
                if status[p] != "crashed":
                    # manifest mismatch / breaker: decode locally on p
                    out.append((f"handoff_fallback[{q}]",
                                with_req(q, (("running", p), nc, nd))))
            elif isinstance(phase, tuple) and phase[0] == "running":
                r = phase[1]
                if status[r] != "crashed":
                    out.append((f"complete[{q}]",
                                with_req(q, ("done", nc, nd + 1))))
                if status[r] == "draining":
                    # drain migration: evacuate and re-route
                    out.append((f"evacuate[{q}]",
                                with_req(q, ("pending", nc, nd))))
        return out

    def invariant(self, state):
        msgs = []
        for q, (phase, _nc, nd) in enumerate(state[1]):
            if nd > 1:
                msgs.append(f"request {q} completed {nd} times "
                            f"(exactly-once broken)")
            if phase == "done" and nd != 1:
                msgs.append(f"request {q} done with {nd} completions")
        return msgs

    def is_goal(self, state):
        return all(phase in _Q_TERMINAL for phase, _nc, _nd in state[1])


# ===================================================================
# Spec 3: ResumeDescriptor failover — fleet/failover.py
# ===================================================================

class ResumeSpec(Spec):
    """No double-commit of a token position across crash/resume.

    A stream of M positions: the serving replica emits tokens past
    `base` (its resume point), the router syncs emitted tokens to the
    client watermark `d`, and a crash replaces the replica with one
    resuming from the descriptor.  State:
    (per-position delivery counts, base, emitted-past-base, d,
    crashes_left).

    Correct resume: the descriptor carries prompt + ALL delivered ids,
    so the replacement resumes from the watermark (`base = d`) and
    re-emits only undelivered positions.  `bug="stale_resume"` resumes
    from the stale base and REWINDS the watermark — the next sync
    re-delivers positions the client already streamed, and PROTO001
    reports the double-committed position."""

    name = "resume"

    def __init__(self, n_positions: int = 3, crash_budget: int = 2,
                 bug: Optional[str] = None):
        self.m = n_positions
        self.crash_budget = crash_budget
        self.bug = bug

    def initial_states(self):
        yield ((0,) * self.m, 0, 0, 0, self.crash_budget)

    def enabled(self, state):
        deliv, base, s, d, crashes = state
        out: List[Action] = []
        if base + s < self.m:
            out.append(("emit", (deliv, base, s + 1, d, crashes)))
        if base + s > d:
            nd = list(deliv)
            for i in range(d, base + s):
                nd[i] = min(nd[i] + 1, 2)  # cap: 2 already violates
            out.append(("sync", (tuple(nd), base, s, base + s, crashes)))
        if crashes > 0 and base + s < self.m:
            if self.bug == "stale_resume":
                # resume from the stale base; watermark rewinds with it
                out.append(("crash_resume",
                            (deliv, base, 0, base, crashes - 1)))
            else:
                out.append(("crash_resume",
                            (deliv, d, 0, d, crashes - 1)))
        return out

    def invariant(self, state):
        deliv = state[0]
        return [f"position {i} delivered {c} times"
                for i, c in enumerate(deliv) if c > 1]

    def is_goal(self, state):
        deliv, base, s, d, _crashes = state
        return base + s == self.m and d == self.m


# ===================================================================
# Spec 4: KVTransport chunked idempotent commit — fleet/transport.py
# ===================================================================

class TransportSpec(Spec):
    """send_paths_chunked under duplicate / reordered / stalled /
    corrupted delivery converges to exactly one manifest-verified copy
    per path.

    State: per-path (in-flight deliveries as a sorted tuple of
    'ok'/'corrupt', sends_left, committed, commit_count, failed), plus
    a global corruption budget (`fleet.transport.page_corrupt`).
    Reordering across paths is free: BFS explores every delivery
    interleaving.

    Safety (PROTO001): commit_count <= 1 per path (the `_committed`
    manifest-key dedup), and a corrupt delivery never commits (the
    manifest verify precedes the commit).  `bug="nonidempotent_commit"`
    commits every ok delivery — the duplicate-final-chunk double-commit
    appears immediately.
    Stuck (PROTO002): every path ends committed or LOUDLY failed even
    when stalls eat the whole retry budget."""

    name = "transport"

    def __init__(self, n_paths: int = 2, retries: int = 2,
                 max_inflight: int = 2, corrupt_budget: int = 1,
                 bug: Optional[str] = None):
        self.k = n_paths
        self.retries = retries
        self.max_inflight = max_inflight
        self.corrupt_budget = corrupt_budget
        self.bug = bug

    def initial_states(self):
        yield (tuple(((), self.retries, False, 0, False)
                     for _ in range(self.k)), self.corrupt_budget)

    def enabled(self, state):
        paths, corrupt = state
        out: List[Action] = []

        def with_path(p, path, dc=0):
            np_ = list(paths)
            np_[p] = path
            return (tuple(np_), corrupt + dc)

        for p, (flight, sends, committed, count, failed) in \
                enumerate(paths):
            room = len(flight) < self.max_inflight
            if sends > 0 and not committed and not failed and room:
                out.append((f"send[{p}]", with_path(
                    p, (tuple(sorted(flight + ("ok",))), sends - 1,
                        committed, count, failed))))
                if corrupt > 0:
                    # fleet.transport.page_corrupt flips this copy
                    out.append((f"send_corrupt[{p}]", with_path(
                        p, (tuple(sorted(flight + ("corrupt",))),
                            sends - 1, committed, count, failed),
                        dc=-1)))
            if flight and room:
                # the network duplicates an in-flight copy
                for kind in sorted(set(flight)):
                    out.append((f"duplicate[{p}:{kind}]", with_path(
                        p, (tuple(sorted(flight + (kind,))), sends,
                            committed, count, failed))))
            for kind in sorted(set(flight)):
                rest = list(flight)
                rest.remove(kind)
                rest = tuple(sorted(rest))
                # fleet.transport.stall: the copy is lost in flight
                out.append((f"stall[{p}:{kind}]", with_path(
                    p, (rest, sends, committed, count, failed))))
                if kind == "corrupt":
                    # manifest verify rejects; nothing commits
                    out.append((f"deliver[{p}:corrupt]", with_path(
                        p, (rest, sends, committed, count, failed))))
                else:
                    if committed and self.bug != "nonidempotent_commit":
                        # _committed dedup: duplicate delivery after a
                        # successful commit is a no-op
                        out.append((f"deliver[{p}:ok]", with_path(
                            p, (rest, sends, True, count, failed))))
                    else:
                        out.append((f"deliver[{p}:ok]", with_path(
                            p, (rest, sends, True, min(count + 1, 2),
                                failed))))
            if (sends == 0 and not flight and not committed
                    and not failed):
                # retry budget exhausted: fail loudly, never hang
                out.append((f"report_failed[{p}]", with_path(
                    p, (flight, sends, committed, count, True))))
        return out

    def invariant(self, state):
        msgs = []
        for p, (_f, _s, _c, count, _failed) in enumerate(state[0]):
            if count > 1:
                msgs.append(f"path {p} committed {count} times "
                            f"(idempotent retry broken)")
        return msgs

    def is_goal(self, state):
        return all((committed or failed) and not flight
                   for flight, _s, committed, _n, failed in state[0])


def ALL_SPECS() -> List[Spec]:
    """The four shipped protocol specs at committed scope."""
    return [HealthSpec(), RouterSpec(), ResumeSpec(), TransportSpec()]


# ===================================================================
# Layer 12b: conformance replay (PROTO003 — spec drift)
# ===================================================================

# transitions the HealthMonitor spec admits (see health.py: probe,
# mark_dead, revive); anything else observed in a drill log is drift
_HEALTH_ADMITTED = {
    ("alive", "suspect"),    # missed probe inside the budget
    ("suspect", "alive"),    # progress resumed / revived
    ("suspect", "dead"),     # budget exhausted
    ("alive", "dead"),       # mark_dead fast path (step() raised)
    ("dead", "alive"),       # revive via add_replica
}


def replay_health_events(events: Sequence[Dict[str, str]],
                         node: str = "drill:health") -> List[Finding]:
    """Replay a HealthMonitor event log (`monitor.events` /
    `monitor.transitions()`) against the spec's admitted transition
    relation.  Initial state per replica is ALIVE (track())."""
    findings: List[Finding] = []
    cur: Dict[str, str] = {}
    for i, ev in enumerate(events):
        rid = str(ev.get("replica_id"))
        state = str(ev.get("state"))
        prev = cur.get(rid, "alive")
        if state not in ("alive", "suspect", "dead"):
            findings.append(make_finding(
                "PROTO003", node,
                f"event {i}: unknown health state {state!r} for "
                f"replica {rid}"))
            continue
        if (prev, state) not in _HEALTH_ADMITTED:
            findings.append(make_finding(
                "PROTO003", node,
                f"event {i}: transition {prev} -> {state} for replica "
                f"{rid} ({ev.get('reason', '')!r}) is not admitted by "
                f"the health spec"))
        cur[rid] = state
    return findings


# router protocol automaton: NEW -admitted-> OPEN; OPEN cycles through
# routing/recovery events or enters HANDOFF; exactly one terminal.
_ROUTER_OPEN_EVENTS = {"routed", "migrated", "recovered"}
_ROUTER_TERMINAL = {"completed", "quarantined", "failed"}
_ROUTER_HANDOFF_CLOSE = {"handoff_committed", "handoff_fallback"}
_ROUTER_KNOWN = ({"admitted", "handoff_started"} | _ROUTER_OPEN_EVENTS
                 | _ROUTER_TERMINAL | _ROUTER_HANDOFF_CLOSE)


def replay_router_protocol(events: Sequence[Dict[str, Any]],
                           node: str = "drill:router",
                           expect_terminal: bool = True) -> List[Finding]:
    """Replay a FleetRouter protocol event log (`router.transitions()`)
    through the request-lifecycle automaton the RouterSpec models:
    admitted first, then routing/handoff/recovery events, then exactly
    one terminal (completed / quarantined / failed) and silence.  With
    `expect_terminal`, an admitted request that never reaches a
    terminal is a dropped completion — the zero-drop property PROTO001
    proves in the model, checked here against reality."""
    findings: List[Finding] = []
    phase: Dict[str, str] = {}  # request_id -> NEW/OPEN/HANDOFF/DONE
    for i, ev in enumerate(events):
        rid = str(ev.get("request_id"))
        name = str(ev.get("event"))
        st = phase.get(rid, "NEW")
        if name not in _ROUTER_KNOWN:
            findings.append(make_finding(
                "PROTO003", node,
                f"event {i}: unknown protocol event {name!r} for "
                f"request {rid}"))
            continue
        if st == "NEW":
            if name == "admitted":
                phase[rid] = "OPEN"
            else:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"event {i}: request {rid} saw {name!r} before "
                    f"'admitted'"))
                phase[rid] = "OPEN"  # resync: report once, keep going
        elif st == "OPEN":
            if name in _ROUTER_OPEN_EVENTS:
                pass
            elif name == "handoff_started":
                phase[rid] = "HANDOFF"
            elif name in _ROUTER_TERMINAL:
                phase[rid] = "DONE"
            else:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"event {i}: request {rid} saw {name!r} outside a "
                    f"handoff"))
        elif st == "HANDOFF":
            if name in _ROUTER_HANDOFF_CLOSE or name == "recovered":
                phase[rid] = "OPEN"
            elif name in _ROUTER_TERMINAL:
                # CircuitOpenError inside _poll_handoffs fails the
                # request; a crash-recovery can quarantine it
                phase[rid] = "DONE"
            else:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"event {i}: request {rid} saw {name!r} with a "
                    f"handoff in flight"))
        elif st == "DONE":
            findings.append(make_finding(
                "PROTO003", node,
                f"event {i}: request {rid} saw {name!r} after its "
                f"terminal event"))
    if expect_terminal:
        for rid, st in phase.items():
            if st != "DONE":
                findings.append(make_finding(
                    "PROTO003", node,
                    f"request {rid} was admitted but never reached a "
                    f"terminal event (dropped completion)"))
    return findings


def replay_transport_commits(events: Sequence[Dict[str, Any]],
                             node: str = "drill:transport"
                             ) -> List[Finding]:
    """Replay a KVTransport commit event log (`transport.transitions()`)
    against the idempotence relation: per manifest key, at most one
    'committed'; 'deduped' only after a commit; 'rejected' never
    commits (it carries no commit)."""
    findings: List[Finding] = []
    committed: Dict[Any, int] = {}
    for i, ev in enumerate(events):
        name = str(ev.get("event"))
        key = ev.get("key")
        if name == "committed":
            committed[key] = committed.get(key, 0) + 1
            if committed[key] > 1:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"event {i}: manifest key {key!r} committed "
                    f"{committed[key]} times (idempotent commit broken)"))
        elif name == "deduped":
            if committed.get(key, 0) < 1:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"event {i}: dedup for manifest key {key!r} with no "
                    f"prior commit"))
        elif name == "rejected":
            pass  # verification rejection commits nothing, by shape
        else:
            findings.append(make_finding(
                "PROTO003", node,
                f"event {i}: unknown transport event {name!r}"))
    return findings


def replay_restore_attempts(attempts: Sequence[Dict[str, Any]],
                            node: str = "drill:restore") -> List[Finding]:
    """Replay the elastic-restore halve-and-replan attempt trail
    (checkpoint._restore `attempts`): every OOM must be followed by a
    replan at half the chunk budget, and exactly the final attempt
    lands."""
    findings: List[Finding] = []
    if not attempts:
        findings.append(make_finding(
            "PROTO003", node, "restore report carries no attempt trail"))
        return findings
    for i, att in enumerate(attempts):
        outcome = att.get("outcome")
        last = i == len(attempts) - 1
        if outcome == "landed":
            if not last:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"attempt {i} landed but {len(attempts) - 1 - i} "
                    f"more attempts follow"))
        elif outcome == "oom":
            if last:
                findings.append(make_finding(
                    "PROTO003", node,
                    f"attempt {i} hit OOM with no replan after it"))
            else:
                want = max(1, int(att.get("chunk_bytes", 0)) // 2)
                got = int(attempts[i + 1].get("chunk_bytes", -1))
                if got != want:
                    findings.append(make_finding(
                        "PROTO003", node,
                        f"attempt {i + 1} replanned at {got} bytes, "
                        f"expected half of {att.get('chunk_bytes')} "
                        f"= {want}"))
        else:
            findings.append(make_finding(
                "PROTO003", node,
                f"attempt {i}: unknown outcome {outcome!r}"))
    return findings
