"""Layer 7: paged-KV auditor.

KV001 — the page-table/refcount consistency audit over the paged decode
cache (kv/pool.py + kv/table.py + serve/generation.py's `_PagedPool`).
The paged layout's safety rests entirely on host bookkeeping: the device
only ever sees an int32 table and a flat arena, so a bookkeeping bug does
not crash — it silently serves one sequence another sequence's K/V, or
writes a live page after it was handed to someone else.  This audit
cross-checks the three structures against each other:

  * every table entry points at a LIVE page (refcount >= 1) inside the
    arena — an entry at a freed page means attention is reading memory
    the allocator may hand out again mid-generation;
  * no page is mapped by more holders than its refcount — two sequences
    mapping one page with refcount 1 means the first retire frees it
    under the second (the "two live sequences without refcount >= 2"
    failure);
  * every trie-committed page reference is live, and counts toward the
    page's refcount alongside its table occurrences;
  * the pool's own free-list/byte-conservation invariants hold
    (`PagePool.check_invariants`: double frees, leaked pages, arena
    bytes != mapped + free bytes), and the table's shape/contiguity
    invariants hold (`PageTable.check_invariants`: a hole inside a row's
    live prefix gathers an unmasked garbage page).

Wired as a session hook like SERVE001/002: `GenerationSession` calls
`check_page_table` at the first decode round and at every retire — the
transitions where refcount drift would next cause a wrong free.
"""

from __future__ import annotations

from typing import List

from .findings import Finding, make_finding


def audit_page_table(pool, table, trie=None,
                     node: str = "kv") -> List[Finding]:
    """KV001 over a live (`PagePool`, `PageTable`[, `PrefixCache` of
    {"page": id} references]) triple.  Returns one finding per violated
    invariant; [] when the bookkeeping is consistent."""
    findings: List[Finding] = []
    for problem in pool.check_invariants():
        findings.append(make_finding("KV001", node, f"pool: {problem}"))
    for problem in table.check_invariants():
        findings.append(make_finding("KV001", node, f"table: {problem}"))

    # holders per page: table occurrences across all slots + trie refs
    holders = {}
    for slot in range(table.max_slots):
        for pid in table.mapped(slot):
            holders.setdefault(pid, []).append(f"slot{slot}")
    if trie is not None:
        for tnode in trie._walk():
            pid = tnode.kv.get("page") if isinstance(tnode.kv, dict) \
                else None
            if pid is None:
                continue  # bucketed-style array commit; nothing to audit
            holders.setdefault(pid, []).append(f"trie@depth{tnode.depth}")

    for pid, who in sorted(holders.items()):
        if not 0 <= pid < pool.n_pages:
            findings.append(make_finding(
                "KV001", node,
                f"page {pid} (held by {', '.join(who)}) is outside the "
                f"arena [0, {pool.n_pages})"))
            continue
        rc = pool.refcount(pid)
        if rc < 1:
            findings.append(make_finding(
                "KV001", node,
                f"page {pid} is mapped by {', '.join(who)} but has "
                f"refcount {rc} (freed under a live holder — the "
                f"allocator can hand it to another sequence)"))
        elif rc < len(who):
            findings.append(make_finding(
                "KV001", node,
                f"page {pid} has {len(who)} holders "
                f"({', '.join(who)}) but refcount {rc}: the first "
                f"release frees it under the remaining holders"))
    return findings
