"""Layer 13: quantized/tiered-KV sanitizer.

The block-scaled int8 KV arena (ops/flash_attention.py::kv_quantize /
kv_dequantize, models gpt+llama paged forwards) and the host memory tier
(kv/tier.py) both fail the same way the paged layout does: silently.
A payload page whose scales went missing dequantizes into garbage; a
decode program that forgot the dequant computes logits on raw int8
codes, off by exactly the per-block scale; a host-tier entry whose
bytes rotted serves a corrupt prefix to every request sharing it.  None
of these crash — they emit plausible wrong tokens.  Three audits:

  * KVQ001 `audit_quant_arena` — structural payload/scale consistency
    over a live arena pytree: int8 payload implies a float32 scale leaf
    whose shape is the payload's with the feature axis divided into
    blocks; scale leaves over a non-int8 payload are equally a desync
    (the exact path must stay scale-free so its programs stay
    jaxpr-identical to pre-quant builds);
  * KVQ002 `audit_quant_program` — jaxpr lint over a compiled paged
    step: no `dot_general` may consume an int8-typed operand.  A
    correct quant program dequantizes (convert + scale multiply) before
    attention, so int8 reaching a dot IS the missing-dequant bug;
  * KVQ003 `audit_tier_roundtrip` — wraps `HostTier.check_invariants`
    (per-entry sha256 manifest re-verification + byte accounting) into
    findings, the same shape KV001 gives the page-table audit.

Wired as session hooks next to KV001: the paged first-decode audit runs
KVQ001/KVQ002 when the arena is quantized, and KVQ003 whenever a host
tier is attached.
"""

from __future__ import annotations

from typing import List

from .findings import Finding, make_finding


def audit_quant_arena(arena, node: str = "kv.quant") -> List[Finding]:
    """KVQ001 over an arena pytree ({"k","v"[,"k_scale","v_scale"]})."""
    import numpy as np

    findings: List[Finding] = []
    for name in ("k", "v"):
        payload = arena.get(name)
        if payload is None:
            findings.append(make_finding(
                "KVQ001", node, f"arena has no {name!r} payload leaf"))
            continue
        scales = arena.get(f"{name}_scale")
        quantized = np.dtype(payload.dtype) == np.int8
        if quantized and scales is None:
            findings.append(make_finding(
                "KVQ001", node,
                f"{name!r} payload is int8 but the arena carries no "
                f"{name}_scale leaf — pages cannot be dequantized"))
            continue
        if not quantized and scales is not None:
            findings.append(make_finding(
                "KVQ001", node,
                f"arena carries {name}_scale over a "
                f"{np.dtype(payload.dtype).name} payload — the exact "
                f"path must stay scale-free (jaxpr-identical contract)"))
            continue
        if not quantized:
            continue
        if np.dtype(scales.dtype) != np.float32:
            findings.append(make_finding(
                "KVQ001", node,
                f"{name}_scale dtype is {np.dtype(scales.dtype).name}, "
                f"expected float32"))
        d = int(payload.shape[-1])
        nb = int(scales.shape[-1])
        if tuple(scales.shape[:-1]) != tuple(payload.shape[:-1]) \
                or nb < 1 or d % nb != 0:
            findings.append(make_finding(
                "KVQ001", node,
                f"{name}_scale shape {tuple(scales.shape)} does not "
                f"block-partition payload shape {tuple(payload.shape)} "
                f"(leading dims must match; head_dim {d} must divide "
                f"into {nb} blocks) — dequant would broadcast scales "
                f"onto the wrong pages"))
    return findings


def _int8_dot_operands(jaxpr) -> List[str]:
    """Descriptions of every dot_general consuming an int8 operand,
    recursing into sub-jaxprs (pjit/cond/scan/remat)."""
    import numpy as np

    hits: List[str] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            for i, iv in enumerate(eqn.invars):
                aval = getattr(iv, "aval", None)
                if aval is not None and \
                        np.dtype(aval.dtype) == np.int8:
                    hits.append(
                        f"dot_general operand {i} has dtype int8 "
                        f"(shape {tuple(aval.shape)})")
        for param in eqn.params.values():
            sub = []
            if hasattr(param, "jaxpr"):
                sub = [param.jaxpr]
            elif isinstance(param, (list, tuple)):
                sub = [p.jaxpr for p in param if hasattr(p, "jaxpr")]
            for s in sub:
                hits.extend(_int8_dot_operands(s))
    return hits


def audit_quant_program(result, node: str = "decode.quant") -> List[Finding]:
    """KVQ002 over a compiled paged step (`get_compiled` result): retrace
    `result.jitted` on its input avals and lint the jaxpr for int8
    operands reaching a `dot_general`.  When the retrace is unavailable
    the audit skips (same policy as SERVE002's mask walk)."""
    try:
        import jax

        traced = jax.make_jaxpr(result.jitted)(*result.in_avals)
    except Exception:
        return []
    return [make_finding(
        "KVQ002", node,
        f"{hit} — int8 K/V reached attention without dequantization "
        f"(kv_dequantize / the quant kernel's in-loop scale multiply "
        f"must run before the score matmul)")
        for hit in _int8_dot_operands(traced.jaxpr)]


def audit_tier_roundtrip(tier, node: str = "kv.tier") -> List[Finding]:
    """KVQ003 over a live `HostTier`: re-verify every entry's sha256
    manifest and the byte accounting."""
    return [make_finding("KVQ003", node, problem)
            for problem in tier.check_invariants()]
