"""Structured findings for the static SPMD analyzer.

Every rule violation the analyzer detects is a `Finding(rule_id, severity,
node, message)`; a pass over one artifact (a solved MetaGraph axis, an
emitted jaxpr, a bucket plan, a memory plan, a pipeline tick schedule)
returns a list of findings, and
`AnalysisReport` aggregates them across passes with PerfDB export and the
raise-on-error gate (`edconfig.analyze_raise` is the escape hatch).

The rule catalog lives HERE (id -> severity/title) so the rule modules,
docs/ANALYZE.md, and the tests share one source of truth; a rule module
emitting an unregistered id is itself a bug and raises immediately.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

# rule_id -> (default severity, one-line title).  docs/ANALYZE.md mirrors
# this table with the full description + escape hatch per rule.
RULES: Dict[str, tuple] = {
    # ---- layer 1: strategy verifier (solved MetaIR, per mesh axis)
    "STRAT000": (SEV_INFO,
                 "strategy layer skipped (compile-cache hit: no MetaGraph)"),
    "STRAT001": (SEV_ERROR,
                 "consumer expects PARTIAL but producer emits R/S "
                 "(no priced reshard materializes a partial)"),
    "STRAT002": (SEV_ERROR,
                 "S(dim) out of tensor rank or not divisible by the "
                 "mesh-axis size"),
    "STRAT003": (SEV_ERROR,
                 "PARTIAL placement escapes at a graph output"),
    "STRAT004": (SEV_ERROR,
                 "PARTIAL unresolved: rides a non-linear consumer, both "
                 "operands of a bilinear op, or a mismatched reduction"),
    "STRAT005": (SEV_ERROR,
                 "solver objective drift: reported edge-comm cost != "
                 "independent assignment_comm_cost recomputation"),
    # ---- layer 2: collective-program linter (emitted jaxpr / comm plans)
    "COLL000": (SEV_WARNING,
                "program lint skipped (emitted jaxpr unavailable)"),
    "COLL001": (SEV_ERROR,
                "collective names a mesh axis that does not exist"),
    "COLL002": (SEV_ERROR,
                "cond branches disagree on their collective programs "
                "(deadlock shape)"),
    "COLL003": (SEV_ERROR,
                "bucket slices do not tile the flat buffer exactly "
                "(gap/overlap/size mismatch)"),
    "COLL004": (SEV_ERROR,
                "int8 operand fed to an arithmetic reduction collective "
                "(missing the two-pass scale)"),
    "COLL005": (SEV_WARNING,
                "collective inside a while-loop predicate (trip counts may "
                "diverge across devices)"),
    # ---- layer 3a: memory-plan verifier (MemoryPlan over solved MetaIR)
    "MEM000": (SEV_INFO,
               "memory layer skipped (no MetaGraph: compile-cache hit or "
               "single-device mesh)"),
    "MEM001": (SEV_ERROR,
               "memory-plan lifetime drift: an interval disagrees with the "
               "independent producer/last-consumer recomputation"),
    "MEM002": (SEV_ERROR,
               "memory-plan size drift: interval bytes != placement-"
               "divided tensor bytes (element-aligned, shards rounded up)"),
    "MEM003": (SEV_ERROR,
               "skyline unsound: overlapping live offsets, or peak below "
               "the sum-of-live lower bound / packed extent"),
    "MEM004": (SEV_ERROR,
               "predicted per-device peak exceeds the HBM budget "
               "(structured remat advisory attached)"),
    "MEM005": (SEV_ERROR,
               "remat rewrite unsound: non-flat/pure chain equation, "
               "non-lowering rewrite, or missing CSE barrier"),
    # ---- layer 3b: pipeline-schedule verifier (tick schedule tables)
    "SCHED001": (SEV_ERROR,
                 "pipeline schedule deadlock: a unit runs before its "
                 "dependency arrives, is scheduled twice, or never runs"),
    "SCHED002": (SEV_ERROR,
                 "pipeline activation stash over bound: in-flight "
                 "microbatches exceed the residual ring or the 1F1B limit"),
    "SCHED003": (SEV_WARNING,
                 "pipeline bubble fraction above the report threshold"),
    # ---- layer 2b: overlapped-flush verifier (comm/overlap.py plans &
    #      isolated flush programs)
    "OVL001": (SEV_ERROR,
               "emission order is not a permutation of the gradient "
               "leaves (a reordered flush would drop/duplicate leaves)"),
    "OVL002": (SEV_ERROR,
               "overlapped flush chain unpinned: consecutive reducing "
               "collectives have no ordering dependency (the "
               "optimization_barrier token chain is broken)"),
    "OVL003": (SEV_WARNING,
               "predict_comm_overlap is on without a measured overlap "
               "fraction for this backend (discount rests on the flat "
               "config guess)"),
    # ---- layer 4: resilience auditor (guard trace parity + checkpoint
    #      commit-protocol integrity, analyze/resilience_rules.py)
    "RES001": (SEV_ERROR,
               "guard-off trace parity broken: a builder's guard-off "
               "program differs from the pre-guard build (the guard must "
               "be a strict opt-in, bitwise-identical when off)"),
    "RES002": (SEV_ERROR,
               "COMMITTED checkpoint fails manifest verification "
               "(missing/corrupt files — resume from it would poison "
               "training state)"),
    "RES003": (SEV_WARNING,
               "stale uncommitted checkpoint debris (dead .tmp_* write "
               "dirs or superseded torn step_N dirs awaiting GC)"),
    # ---- layer 5: serving auditor (decode-step cache donation,
    #      analyze/serve_rules.py)
    "SERVE001": (SEV_WARNING,
                 "decode-step KV cache input not donated (every token "
                 "pays a full-cache HBM copy instead of an in-place "
                 "XLA update)"),
    "SERVE002": (SEV_ERROR,
                 "chunked-prefill contract broken: staging cache not "
                 "donated (warning), attention window not length-masked "
                 "(stale-row leakage — restored/garbage cache rows could "
                 "leak into live logits), or prefix-trie refcount/byte "
                 "accounting drift"),
    "SERVE003": (SEV_ERROR,
                 "speculative rewind contract broken: verify step not "
                 "length-masked past the committed positions (warning "
                 "for non-donated cache), accepted-prefix bookkeeping "
                 "advanced past the first draft/target mismatch (output "
                 "would diverge from plain greedy), or a paged rollback "
                 "left a table row pointing at a released page"),
    # ---- layer 7: paged-KV auditor (page-table/refcount consistency,
    #      analyze/kv_rules.py)
    "KV001": (SEV_ERROR,
              "paged-KV bookkeeping broken: a table entry points at a "
              "freed page, a page has more holders (table rows + trie "
              "refs) than its refcount, or the pool/table invariants "
              "(double free, leaked page, byte conservation, hole in a "
              "row's live prefix) fail — attention would read or the "
              "allocator would reuse another sequence's K/V, "
              "bitwise-silently"),
    # ---- layer 6: fleet auditor (multi-replica routing / KV handoff /
    #      drain hygiene, analyze/fleet_rules.py)
    "FLEET001": (SEV_ERROR,
                 "request routed to an ineligible replica (circuit "
                 "breaker OPEN or already draining) — load steered into "
                 "a replica that is shedding or leaving"),
    "FLEET002": (SEV_ERROR,
                 "KV page handoff fails manifest verification (token "
                 "ids, sha256, or byte count disagree) — a corrupt page "
                 "committed to a live trie poisons every request "
                 "sharing that prefix, bitwise-silently"),
    "FLEET003": (SEV_WARNING,
                 "drained replica's trie still holds pinned pages "
                 "(pin/unpin imbalance): unevictable orphans keep device "
                 "memory from releasing"),
    "FLEET004": (SEV_ERROR,
                 "request dispatched to a replica the health monitor had "
                 "marked DEAD — the router's eligibility filter must "
                 "exclude dead replicas exactly like OPEN breakers; the "
                 "request would strand on a corpse"),
    "FLEET005": (SEV_ERROR,
                 "resume descriptor inconsistent with its original "
                 "request (resubmitted prefix != prompt + emitted ids, "
                 "budget overrun, or eos already emitted) — recovery "
                 "would silently change output tokens"),
    # ---- layer 8: redistribution auditor (reshard plans + restored
    #      shardings, analyze/reshard_rules.py)
    "RESHARD001": (SEV_ERROR,
                   "redistribution plan peak live bytes exceed the "
                   "chunked bound O(max(src_shard, dst_shard) + chunk) — "
                   "the plan silently degenerated toward global "
                   "materialization, the replicated-restore OOM hazard "
                   "the reshard substrate exists to remove"),
    "RESHARD002": (SEV_ERROR,
                   "restored leaf sharding disagrees with the template "
                   "spec — the caller's jit will silently re-lay-out "
                   "(or OOM re-gathering) every step, and a replicated "
                   "leaf that should be sharded holds n_devices x its "
                   "byte budget"),
    # ---- layer 9: simulator/autoscaler auditor (prediction fidelity +
    #      control-loop stability, analyze/sim_rules.py)
    "SIM001": (SEV_ERROR,
               "simulator prediction drifted beyond the committed "
               "relative-error bound against a measured bench actual — "
               "the capacity planner and autoscaler are steering the "
               "fleet on numbers the hardware no longer agrees with "
               "(stale calibration, an uncalibrated residual domain, or "
               "a cost-model regression)"),
    "SIM002": (SEV_ERROR,
               "autoscaler flap: opposite-direction scale actuations "
               "inside the hysteresis window (an A-B-A oscillation) — "
               "each reversal pays a drain + page-migration + spin-up "
               "round trip for zero steady-state change, so the "
               "confirm/cooldown gates are mis-tuned or bypassed"),
    # ---- layer 10: pruned-discovery auditor (propagation-group and
    #      cache transfers, analyze/discovery_rules.py)
    "DISC001": (SEV_ERROR,
                "propagation-group member shapes are incompatible with "
                "the instantiated representative rule (row/rank mismatch, "
                "halo wider than a member shard, or a size-sensitive rule "
                "transferred across shapes) — the pruner reused a rule "
                "the member could not have discovered"),
    "DISC002": (SEV_WARNING,
                "execution discovery fired for a primitive that has an "
                "analytic preset — the preset declined this instance, so "
                "the compile pays the probe harness for an op the preset "
                "bank claims to cover"),
    # ---- layer 11: donation/aliasing sanitizer (analyze/alias_rules.py)
    "ALIAS001": (SEV_ERROR,
                 "donated invar used after its consuming dispatch: a "
                 "later equation (or the program output) reads a buffer "
                 "XLA is free to overwrite in place — bitwise-correct on "
                 "CPU (donation ignored) and silently corrupt on TPU"),
    "ALIAS002": (SEV_ERROR,
                 "double donation: two donated invars alias one "
                 "underlying buffer (or one output claims two donated "
                 "inputs) — XLA reuses the storage twice and one write "
                 "clobbers the other"),
    "ALIAS003": (SEV_ERROR,
                 "donation declared but unhonorable: the donated input "
                 "matches no output's shape/dtype/sharding, so XLA "
                 "silently copies instead of updating in place — the "
                 "in-place win the donation was written for never "
                 "happens"),
    "ALIAS004": (SEV_ERROR,
                 "donated device buffer reachable from a live host "
                 "reference across a step boundary (snapshot, hot-page "
                 "export, trie-held staging row): the next donating "
                 "dispatch invalidates storage the host still reads"),
    # ---- layer 12: fleet protocol model checker + concurrency
    #      sanitizer (analyze/modelcheck.py + analyze/protocol_rules.py)
    "PROTO001": (SEV_ERROR,
                 "protocol safety violation: exhaustive small-scope "
                 "exploration reached a state that drops an admitted "
                 "request, commits the same token position twice, or "
                 "accepts a corrupt chunk — the shortest counterexample "
                 "interleaving is attached"),
    "PROTO002": (SEV_ERROR,
                 "protocol stuck state: a reachable state has no path to "
                 "the goal (no enabled action, or a livelock cycle) — an "
                 "admitted request would wait forever instead of "
                 "completing, failing, or quarantining loudly"),
    "PROTO003": (SEV_ERROR,
                 "spec drift: a transition observed in a real drill "
                 "event log is not admitted by the protocol spec — "
                 "either the implementation grew a behavior the model "
                 "checker never explores, or the spec rotted into "
                 "parallel documentation"),
    "PROTO004": (SEV_ERROR,
                 "private fleet state read from outside the owning "
                 "class: observer/metrics code reaches into a router/"
                 "replica/monitor's underscore attributes instead of a "
                 "snapshot API — a data race the moment replicas live "
                 "in another process"),
    "PROTO005": (SEV_ERROR,
                 "shared fleet structure mutated outside the owning "
                 "class's methods: external writes to rings, in-flight "
                 "tables, or commit maps bypass the single-writer "
                 "protocol the model checker verifies"),
    # ---- layer 13: quantized/tiered KV sanitizer
    #      (analyze/kv_quant_rules.py)
    "KVQ001": (SEV_ERROR,
               "quantized arena desync: scale arena missing/mis-shaped "
               "for its int8 payload (or scales present over a "
               "non-quantized payload) — dequantized K/V would be "
               "garbage at exactly the pages the shapes disagree on, "
               "bitwise-silently"),
    "KVQ002": (SEV_ERROR,
               "quantized decode program feeds int8 K/V into a "
               "dot_general without dequantizing (no int8->float "
               "convert/scale multiply on the operand path) — logits "
               "would be computed on raw quantized codes, off by the "
               "per-block scale"),
    "KVQ003": (SEV_ERROR,
               "host-tier round-trip integrity broken: a tier entry's "
               "stored bytes disagree with its sha256 manifest, or the "
               "tier's byte accounting drifted from its entries — "
               "promotion would serve corrupt K/V (or the budget gate "
               "lies)"),
    # ---- analyzer driver (analyze/driver.py)
    "DRV001": (SEV_WARNING,
               "unused inline suppression: an `# easydist: disable=...` "
               "comment names a rule that produced no finding on that "
               "line — stale suppressions hide future regressions"),
    "DRV002": (SEV_WARNING,
               "stale baseline entry: analyze_baseline.json carries a "
               "fingerprint matching no current finding — the debt was "
               "paid (or the code moved); `--refresh-baseline` prunes it"),
}

# layer index: (layer label, ordering key, rule-id prefixes, escape hatch).
# docs/ANALYZE.md's per-rule index table is generated from RULES + this
# table (tests/test_analyze/test_docs_drift.py keeps them in sync).
KILL_SWITCH = "EASYDIST_ANALYZE=0"
RAISE_SWITCH = "EASYDIST_ANALYZE_RAISE=0"

LAYERS: List[tuple] = [
    ("1 strategy", ("STRAT",)),
    ("2 collectives", ("COLL",)),
    ("2b overlap", ("OVL",)),
    ("3a memory", ("MEM",)),
    ("3b schedule", ("SCHED",)),
    ("4 resilience", ("RES",)),
    ("5 serving", ("SERVE",)),
    ("6 fleet", ("FLEET",)),
    ("7 paged KV", ("KV",)),
    ("8 reshard", ("RESHARD",)),
    ("9 simulator", ("SIM",)),
    ("10 discovery", ("DISC",)),
    ("11 aliasing", ("ALIAS",)),
    ("12 protocol", ("PROTO",)),
    ("13 kv quant", ("KVQ",)),
    ("driver", ("DRV",)),
]


def layer_of(rule_id: str) -> str:
    """Layer label for a rule id (longest matching registered prefix)."""
    best = ""
    label = "?"
    for name, prefixes in LAYERS:
        for p in prefixes:
            if rule_id.startswith(p) and len(p) > len(best):
                best, label = p, name
    return label


def rule_index_rows() -> List[tuple]:
    """(layer, rule_id, severity, escape hatch) rows for every registered
    rule, in catalog order — the docs/ANALYZE.md index table's source."""
    rows = []
    for rule_id, (sev, _title) in RULES.items():
        hatch = KILL_SWITCH if sev != SEV_ERROR else (
            f"{KILL_SWITCH} / {RAISE_SWITCH}")
        rows.append((layer_of(rule_id), rule_id, sev, hatch))
    return rows


@dataclass(frozen=True)
class Finding:
    """One rule violation at one graph/jaxpr location.  `path`/`line`
    are optional source coordinates (the AST lint and the driver's
    suppression/SARIF machinery use them; artifact-level rules leave
    them unset)."""

    rule_id: str
    severity: str
    node: str
    message: str
    path: Optional[str] = None
    line: Optional[int] = None

    def __post_init__(self):
        if self.rule_id not in RULES:
            raise ValueError(f"unregistered analyzer rule id {self.rule_id!r}")
        if self.severity not in (SEV_ERROR, SEV_WARNING, SEV_INFO):
            raise ValueError(f"bad severity {self.severity!r}")

    def __str__(self) -> str:
        where = f"{self.path}:{self.line}: " if self.path else ""
        return (f"[{self.rule_id}:{self.severity}] {where}{self.node}: "
                f"{self.message}")

    def fingerprint(self) -> str:
        """Stable identity for baselining: rule + location + node.  The
        message is EXCLUDED so a reworded diagnostic doesn't churn the
        baseline, and the line number is excluded so unrelated edits
        above a legacy finding don't un-baseline it."""
        return f"{self.rule_id}|{self.path or ''}|{self.node}"


def make_finding(rule_id: str, node: str, message: str,
                 severity: Optional[str] = None,
                 path: Optional[str] = None,
                 line: Optional[int] = None) -> Finding:
    """Finding with the rule's registered default severity."""
    return Finding(rule_id, severity or RULES[rule_id][0], node, message,
                   path=path, line=line)


class AnalysisError(RuntimeError):
    """Raised when a report carries error-severity findings and raising is
    enabled (`edconfig.analyze_raise`, EASYDIST_ANALYZE_RAISE=0 to opt out)."""

    def __init__(self, report: "AnalysisReport"):
        self.report = report
        errors = report.errors()
        lines = "\n  ".join(str(f) for f in errors[:20])
        more = f"\n  ... and {len(errors) - 20} more" if len(errors) > 20 else ""
        super().__init__(
            f"static analysis found {len(errors)} error-severity finding(s) "
            f"(set EASYDIST_ANALYZE_RAISE=0 to demote to logging):\n  "
            f"{lines}{more}")


class AnalysisReport:
    """Aggregated findings of one analyze() run."""

    def __init__(self, findings: Iterable[Finding] = ()):
        self.findings: List[Finding] = list(findings)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEV_ERROR]

    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEV_WARNING]

    def counts(self) -> Dict[str, int]:
        out = {SEV_ERROR: 0, SEV_WARNING: 0, SEV_INFO: 0}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def rule_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule_id] = out.get(f.rule_id, 0) + 1
        return out

    def summary(self) -> str:
        c = self.counts()
        head = (f"analyze: {c[SEV_ERROR]} error(s), {c[SEV_WARNING]} "
                f"warning(s), {c[SEV_INFO]} info")
        if not self.findings:
            return head + " — clean"
        return head + "\n" + "\n".join(f"  {f}" for f in self.findings)

    def raise_on_errors(self) -> "AnalysisReport":
        """Raise AnalysisError if any error-severity finding; returns self
        otherwise (chaining).  Callers gate on `edconfig.analyze_raise`."""
        if self.errors():
            raise AnalysisError(self)
        return self

    def export_to_perfdb(self, sub_key: str = "analyze",
                         db: Optional[object] = None) -> Dict[str, object]:
        """Persist counts + findings under ("analyze_stats", sub_key) so the
        lint evidence lands next to step times and comm_stats."""
        from easydist_tpu.runtime.perfdb import PerfDB

        payload: Dict[str, object] = {
            "counts": self.counts(),
            "rules": self.rule_counts(),
            # cap the stored detail: the counts are the gate, the first
            # findings are the debugging breadcrumb — findings_truncated
            # records how many fell off the cap so a capped export can't
            # masquerade as the full list
            "findings": [(f.rule_id, f.severity, f.node, f.message)
                         for f in self.findings[:50]],
            "findings_truncated": max(0, len(self.findings) - 50),
        }
        db = db or PerfDB()
        db.record_op_perf("analyze_stats", sub_key, payload)
        try:
            db.persist()
        except Exception:  # a read-only DB path must not break analysis
            pass
        return payload
