"""Layer 9: simulator/autoscaler auditor — prediction fidelity and
control-loop stability (`easydist_tpu.sim`).

The sim stack closes a loop: the simulator predicts, the planner ranks,
the autoscaler actuates.  Two failure shapes poison the whole loop:

  SIM001 (error)  a simulator prediction whose relative error against a
                  measured bench actual exceeds the committed bound
                  (`sim.simulate.SIM_REL_ERROR_BOUND`).  The planner and
                  the autoscaler both consume these predictions; drift
                  past the bound means the fleet is being sized on
                  numbers the hardware no longer agrees with — a stale
                  calibration datasheet, a residual domain that was
                  never fit, or a cost-model regression.  `bench.py
                  --simulate` gates on zero SIM001 findings.
  SIM002 (error)  autoscaler flap: a scale actuation in one direction
                  followed by an actuation in the OPPOSITE direction
                  within the hysteresis window (cooldown + confirm
                  ticks).  Every reversal pays a full drain +
                  hot-page-migration + spin-up round trip for zero
                  steady-state change.  The confirm/cooldown gates exist
                  precisely to make this impossible; an A-B-A sequence
                  in the decision log means they are mis-tuned or
                  bypassed.  `bench.py --autoscale` gates on zero SIM002
                  findings over the ramp drill's decision log.

Both rules audit plain data surfaces (a list of prediction rows, the
autoscaler's decision log), so goldens are cheap fixtures — the same
property every other late-layer auditor in this package keeps.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

from .findings import Finding, make_finding

__all__ = ["audit_prediction", "audit_scale_decisions"]


def audit_prediction(rows: Sequence[Dict[str, Any]],
                     bound: float = None,
                     node: str = "sim") -> List[Finding]:
    """SIM001 over validation rows, each
    ``{"preset": str, "predicted_s": float, "measured_s": float}``
    (extra keys pass through untouched).  A row whose relative error
    ``|predicted - measured| / measured`` exceeds `bound` fires; a row
    with a non-positive or missing measurement also fires, because an
    unmeasurable preset cannot have been validated at all."""
    if bound is None:
        from easydist_tpu.sim.simulate import SIM_REL_ERROR_BOUND
        bound = SIM_REL_ERROR_BOUND
    findings: List[Finding] = []
    for row in rows:
        preset = row.get("preset", "?")
        where = f"{node}.preset[{preset}]"
        predicted = row.get("predicted_s")
        measured = row.get("measured_s")
        if predicted is None or measured is None or float(measured) <= 0.0:
            findings.append(make_finding(
                "SIM001", where,
                f"preset {preset!r} has no usable measurement "
                f"(predicted={predicted!r}, measured={measured!r}) — an "
                f"unmeasured preset cannot count as validated"))
            continue
        rel = abs(float(predicted) - float(measured)) / float(measured)
        if rel > bound:
            findings.append(make_finding(
                "SIM001", where,
                f"prediction {float(predicted):.6g}s vs measured "
                f"{float(measured):.6g}s: relative error {rel:.3f} "
                f"exceeds the committed bound {bound:.3f} — recalibrate "
                f"(bench.py --simulate refits the residual) or fix the "
                f"cost-model regression"))
    return findings


def audit_scale_decisions(decisions: Sequence[Dict[str, Any]],
                          window: int = None,
                          node: str = "autoscale") -> List[Finding]:
    """SIM002 over an `Autoscaler.decision_log`: entries carry ``tick``
    and ``action`` ("scale_up" / "scale_down" / "hold").  A pair of
    opposite-direction actuations FEWER than `window` ticks apart is a
    flap.  `window` defaults to confirm + cooldown of the default
    `AutoscaleConfig`: the gates force cooldown suppressions then fresh
    confirmations, so the earliest legitimate reversal is exactly
    `window` ticks after the prior actuation — anything strictly inside
    means the gates were mis-tuned or bypassed."""
    if window is None:
        from easydist_tpu.sim.autoscale import AutoscaleConfig
        cfg = AutoscaleConfig()
        window = cfg.confirm_evals + cfg.cooldown_evals
    findings: List[Finding] = []
    last_dir = 0
    last_tick = None
    for d in decisions:
        action = d.get("action")
        if action not in ("scale_up", "scale_down"):
            continue
        direction = 1 if action == "scale_up" else -1
        tick = int(d.get("tick", 0))
        if (last_dir != 0 and direction == -last_dir
                and last_tick is not None
                and tick - last_tick < window):
            findings.append(make_finding(
                "SIM002", f"{node}.tick[{tick}]",
                f"{action} at tick {tick} reverses the "
                f"{'scale_up' if last_dir > 0 else 'scale_down'} at tick "
                f"{last_tick} within the {window}-tick hysteresis "
                f"window — an A-B-A flap; each reversal pays a drain + "
                f"page-migration + spin-up round trip for nothing"))
        last_dir = direction
        last_tick = tick
    return findings
