"""Layer 6: fleet auditor — routing health, KV handoff integrity, drain
hygiene (`easydist_tpu.fleet`).

Three failure shapes a multi-replica serving fleet adds on top of the
single-session audits:

  FLEET001 (error)   a request routed to a replica whose circuit breaker
                     was OPEN or that was already draining.  The router's
                     eligibility filter exists precisely to prevent this;
                     a decision that slipped through means load is being
                     steered into a replica that is shedding or leaving —
                     the request will burn a timeout or an admission error
                     instead of being served.
  FLEET002 (error)   a KV page handoff whose payload disagrees with its
                     sha256 manifest (token ids, digest, or byte count).
                     A corrupt page committed into a live trie poisons
                     every future request sharing that prefix — bitwise-
                     silently, because restore skips recompute.
  FLEET003 (warning) a drained replica's trie still holds pinned pages.
                     Drain retires every slot and every retirement unpins;
                     leftover refcounts mean a pin/unpin imbalance — the
                     pages can never be evicted and the drained session's
                     device memory never fully releases.

All three audit plain data surfaces (the router's decision log, a
transfer manifest + payload, a drained session's tries), so goldens are
cheap fixtures, not compiled programs.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .findings import Finding, make_finding

__all__ = ["audit_routing", "audit_page_handoff", "audit_drained_session"]


def audit_routing(decisions: Sequence[Dict[str, object]],
                  node: str = "fleet") -> List[Finding]:
    """FLEET001 over a router decision log: every entry names the chosen
    replica and the breaker/drain state OBSERVED at decision time."""
    findings: List[Finding] = []
    for d in decisions:
        rid = d.get("replica_id")
        where = f"{node}.request[{d.get('request_id')}]"
        if d.get("breaker_state") == "open":
            findings.append(make_finding(
                "FLEET001", where,
                f"routed to replica {rid!r} whose circuit breaker was "
                f"OPEN — the eligibility filter must exclude tripped "
                f"replicas"))
        if d.get("draining"):
            findings.append(make_finding(
                "FLEET001", where,
                f"routed to replica {rid!r} that was already draining — "
                f"its session rejects the submit and the request "
                f"bounces"))
    return findings


def audit_page_handoff(manifest: Dict[str, object], path,
                       node: str = "handoff") -> List[Finding]:
    """FLEET002 over one transfer: recompute every page digest against
    the manifest (fleet.transport.verify_manifest does the hashing)."""
    from easydist_tpu.fleet.transport import verify_manifest

    return [make_finding("FLEET002", node, problem)
            for problem in verify_manifest(manifest, path)]


def audit_drained_session(session, node: str = "drain") -> List[Finding]:
    """FLEET003 over a drained session's tries: with no live slots left,
    every page must be unpinned (refcount 0) — pinned leftovers are
    unevictable orphans.  Also folds in the trie's own bookkeeping audit
    (`check_invariants`) since drain is the natural audit point."""
    findings: List[Finding] = []
    if not session.is_drained:
        return [make_finding(
            "FLEET003", node,
            "drain audit ran on a session that still holds live work "
            "(queued/prefilling/decoding) — audit after is_drained")]
    for bucket, pool in getattr(session, "_pools", {}).items():
        trie = getattr(pool, "trie", None)
        if trie is None:
            continue
        where = f"{node}.bucket[{bucket}]"
        for n in trie._walk():
            if n.refcount > 0:
                findings.append(make_finding(
                    "FLEET003", where,
                    f"orphaned pinned page at depth {n.depth} "
                    f"(refcount {n.refcount} with zero live slots): "
                    f"pin/unpin imbalance leaves it unevictable"))
        for problem in trie.check_invariants():
            findings.append(make_finding("FLEET003", where, problem))
    return findings
