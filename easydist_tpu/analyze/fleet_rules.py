"""Layer 6: fleet auditor — routing health, KV handoff integrity, drain
hygiene, failover correctness (`easydist_tpu.fleet`).

Failure shapes a multi-replica serving fleet adds on top of the
single-session audits:

  FLEET001 (error)   a request routed to a replica whose circuit breaker
                     was OPEN or that was already draining.  The router's
                     eligibility filter exists precisely to prevent this;
                     a decision that slipped through means load is being
                     steered into a replica that is shedding or leaving —
                     the request will burn a timeout or an admission error
                     instead of being served.
  FLEET002 (error)   a KV page handoff whose payload disagrees with its
                     sha256 manifest (token ids, digest, or byte count).
                     A corrupt page committed into a live trie poisons
                     every future request sharing that prefix — bitwise-
                     silently, because restore skips recompute.
  FLEET003 (warning) a drained replica's trie still holds pinned pages.
                     Drain retires every slot and every retirement unpins;
                     leftover refcounts mean a pin/unpin imbalance — the
                     pages can never be evicted and the drained session's
                     device memory never fully releases.
  FLEET004 (error)   a request dispatched to a replica the health monitor
                     had marked DEAD.  DEAD must gate eligibility exactly
                     like an OPEN breaker; a decision showing "dead" means
                     load was steered onto a corpse and the request
                     strands until some other layer times it out.
  FLEET005 (error)   a crash/evacuate resume descriptor that disagrees
                     with its original request: the resubmitted prefix is
                     not exactly prompt + already-emitted ids, the emitted
                     ids already exhaust the budget, or they already
                     contain eos.  Any of these means the "recovered"
                     continuation would differ from the uninterrupted
                     run — a silent bitwise break, the one thing the
                     failover layer exists to prevent.

All of these audit plain data surfaces (the router's decision log, a
transfer manifest + payload, a drained session's tries, a resume
descriptor), so goldens are cheap fixtures, not compiled programs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .findings import Finding, make_finding

__all__ = ["audit_routing", "audit_page_handoff", "audit_drained_session",
           "audit_resume"]


def audit_routing(decisions: Sequence[Dict[str, object]],
                  node: str = "fleet") -> List[Finding]:
    """FLEET001 over a router decision log: every entry names the chosen
    replica and the breaker/drain state OBSERVED at decision time."""
    findings: List[Finding] = []
    for d in decisions:
        rid = d.get("replica_id")
        where = f"{node}.request[{d.get('request_id')}]"
        if d.get("breaker_state") == "open":
            findings.append(make_finding(
                "FLEET001", where,
                f"routed to replica {rid!r} whose circuit breaker was "
                f"OPEN — the eligibility filter must exclude tripped "
                f"replicas"))
        if d.get("draining"):
            findings.append(make_finding(
                "FLEET001", where,
                f"routed to replica {rid!r} that was already draining — "
                f"its session rejects the submit and the request "
                f"bounces"))
        if d.get("health") == "dead":
            findings.append(make_finding(
                "FLEET004", where,
                f"dispatched to replica {rid!r} the health monitor had "
                f"marked DEAD — eligibility must exclude dead replicas "
                f"exactly like OPEN breakers"))
    return findings


def audit_resume(descriptor: Dict[str, object],
                 resume_prompt: Optional[Sequence[int]] = None,
                 node: str = "resume") -> List[Finding]:
    """FLEET005 over one resume descriptor (fleet/failover.py
    `ResumeDescriptor.as_dict()` shape) and optionally the exact token
    prefix about to be resubmitted.  The bitwise-recovery contract:
    resubmit == prompt + already-emitted ids, with budget left and no
    eos in the emitted stream."""
    findings: List[Finding] = []
    where = f"{node}.request[{descriptor.get('request_id')}]"
    prompt = [int(t) for t in descriptor.get("prompt", [])]
    ids = [int(t) for t in descriptor.get("ids", [])]
    max_new = descriptor.get("max_new")
    eos_id = descriptor.get("eos_id")
    if resume_prompt is not None \
            and [int(t) for t in resume_prompt] != prompt + ids:
        findings.append(make_finding(
            "FLEET005", where,
            f"resubmitted prefix ({len(list(resume_prompt))} tokens) is "
            f"not prompt + emitted ids ({len(prompt)}+{len(ids)} "
            f"tokens) — the continuation would diverge from the "
            f"uninterrupted run"))
    if isinstance(max_new, int) and len(ids) >= max_new:
        findings.append(make_finding(
            "FLEET005", where,
            f"descriptor resumes with no budget left ({len(ids)} emitted "
            f">= max_new {max_new}) — the request already finished as "
            f"'length' and must not resubmit"))
    if eos_id is not None and eos_id in ids:
        findings.append(make_finding(
            "FLEET005", where,
            f"emitted ids already contain eos {eos_id} — the request "
            f"already finished and a resume would generate tokens past "
            f"the stop"))
    return findings


def audit_page_handoff(manifest: Dict[str, object], path,
                       node: str = "handoff") -> List[Finding]:
    """FLEET002 over one transfer: recompute every page digest against
    the manifest (fleet.transport.verify_manifest does the hashing)."""
    from easydist_tpu.fleet.transport import verify_manifest

    return [make_finding("FLEET002", node, problem)
            for problem in verify_manifest(manifest, path)]


def audit_drained_session(session, node: str = "drain") -> List[Finding]:
    """FLEET003 over a drained session's tries: with no live slots left,
    every page must be unpinned (refcount 0) — pinned leftovers are
    unevictable orphans.  Also folds in the trie's own bookkeeping audit
    (`check_invariants`) since drain is the natural audit point."""
    findings: List[Finding] = []
    if not session.is_drained:
        return [make_finding(
            "FLEET003", node,
            "drain audit ran on a session that still holds live work "
            "(queued/prefilling/decoding) — audit after is_drained")]
    for bucket, pool in getattr(session, "_pools", {}).items():
        trie = getattr(pool, "trie", None)
        if trie is None:
            continue
        where = f"{node}.bucket[{bucket}]"
        for n in trie._walk():
            if n.refcount > 0:
                findings.append(make_finding(
                    "FLEET003", where,
                    f"orphaned pinned page at depth {n.depth} "
                    f"(refcount {n.refcount} with zero live slots): "
                    f"pin/unpin imbalance leaves it unevictable"))
        for problem in trie.check_invariants():
            findings.append(make_finding("FLEET003", where, problem))
    return findings
