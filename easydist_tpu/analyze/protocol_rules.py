"""Analyze layer 12c: host-code concurrency sanitizer (PROTO004/005).

ROADMAP item 4 moves replicas out of this process; the day that lands,
every direct reach into another component's private state becomes a
data race (or simply impossible — the attribute lives across a wire).
The live protocols already publish observer-safe surfaces — the
router's `stats()` / `live_decode_snapshot()` / `inflight_count`, the
health monitor's `snapshot()`, `ServeMetrics.export()` — and the
Autoscaler's MetricsView consumes exactly those.  This lint enforces
that snapshot-only contract repo-wide, statically, the same way the
layer-11 AST lint enforces the donation discipline:

PROTO004 — a *read* of private fleet state through another object:
    `router._inflight`, `self.router._decode_replicas()`,
    `monitor._replicas`, ... from observer code.  `self._x` is the
    owning class touching its own state and never flags; the receiver
    must be a different object (`self.router._x` flags: the private
    segment is ONE HOP past the boundary).

PROTO005 — a *mutation* of a shared fleet structure from outside the
    owning class: assignment/del/augmented-assignment targeting such a
    chain, subscript stores through it, or a mutator-method call on it
    (`router._inflight.pop(...)`, `fleet._handoffs.append(...)`).
    Single-writer is the property the RouterSpec/TransportSpec
    exploration relies on; an outside writer invalidates the model.

The lint fires only on fleet-shaped reaches — the attribute is one of
the known shared structures, or the receiver's terminal name is fleet
vocabulary (`router`, `monitor`, `transport`, ...) — so private
attributes in unrelated subsystems (jax internals, trie nodes inside
their own module) stay out of scope.  Per-file entry point
`lint_file_concurrency` mirrors `alias_rules.lint_file_donation` and
rides the same driver cache/suppression/baseline machinery.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional

from .findings import Finding, make_finding
from .alias_rules import _expr_key

# the shared fleet structures the specs model — reaching these by name
# flags regardless of the receiver's spelling
_SHARED_FLEET_ATTRS = {
    "_inflight", "_handoffs", "_replicas", "_ring", "_prefill_ring",
    "_committed", "_next_request_id", "_decode_replicas",
    "_prefill_replicas", "_eligible", "_last_probe_t", "_rng",
}

# receivers whose private attributes are fleet state even when the
# attribute itself is not in the curated set (`self.router._anything`)
_FLEET_RECEIVERS = {
    "router", "fleet", "monitor", "health", "transport", "replica",
    "rep", "breaker",
}

# method names that mutate their receiver in place: a call through a
# private fleet chain is a write, not a read
_MUTATORS = {
    "append", "pop", "clear", "add", "remove", "update", "extend",
    "insert", "setdefault", "popitem", "discard",
}

_OWN_ROOTS = ("self", "cls")


def _is_private(attr: str) -> bool:
    return attr.startswith("_") and not attr.startswith("__")


def _receiver_terminal(key: str) -> str:
    return key.rsplit(".", 1)[-1]


def _is_fleet_reach(receiver_key: str, attr: str) -> bool:
    if attr in _SHARED_FLEET_ATTRS:
        return True
    return _receiver_terminal(receiver_key).lower() in _FLEET_RECEIVERS


class _ConcurrencyLint(ast.NodeVisitor):
    """Collect private cross-object fleet reaches with read/write
    classification.  Needs parent context for three shapes —
    `x._a.append(...)` (mutator call), `x._a[k] = v` (subscript store),
    `x._a += v` (augmented target) — so the visitor threads a small
    amount of ancestry instead of a full parent map."""

    def __init__(self, rel: str):
        self.rel = rel
        self.findings: List[Finding] = []
        self._reported: set = set()  # (line, key) — one finding per site

    # -- helpers ------------------------------------------------------
    def _flag(self, rule: str, node: ast.AST, chain: str, how: str):
        site = (node.lineno, chain, rule)
        if site in self._reported:
            return
        self._reported.add(site)
        if rule == "PROTO004":
            msg = (f"`{chain}` reads private fleet state across an "
                   f"object boundary ({how}) — observers must consume "
                   f"a snapshot surface (stats()/snapshot()/"
                   f"live_decode_snapshot()), not live structures that "
                   f"move out-of-process with ROADMAP item 4")
        else:
            msg = (f"`{chain}` mutates a shared fleet structure from "
                   f"outside its owning class ({how}) — single-writer "
                   f"is the invariant the layer-12 model checker "
                   f"verifies; route the change through the owner's "
                   f"methods")
        self.findings.append(make_finding(
            rule, f"{self.rel}:{node.lineno}", msg,
            path=self.rel, line=node.lineno))

    def _private_reach(self, node: ast.AST):
        """(attribute node, receiver key, full chain) when `node` is a
        private cross-object fleet reach; None otherwise."""
        if not isinstance(node, ast.Attribute) or not _is_private(node.attr):
            return None
        receiver = _expr_key(node.value)
        if receiver is None or receiver in _OWN_ROOTS:
            return None  # self._x / cls._x: the owner touching itself
        if not _is_fleet_reach(receiver, node.attr):
            return None
        return node, receiver, f"{receiver}.{node.attr}"

    # -- write shapes -------------------------------------------------
    def visit_Assign(self, node):
        for tgt in node.targets:
            self._visit_store_target(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._visit_store_target(node.target)
        self.visit(node.value)

    def visit_Delete(self, node):
        for tgt in node.targets:
            self._visit_store_target(tgt)

    def _visit_store_target(self, tgt):
        hit = self._private_reach(tgt)
        if hit is not None:
            _n, _r, chain = hit
            self._flag("PROTO005", tgt, chain, "assignment target")
            return
        if isinstance(tgt, ast.Subscript):
            hit = self._private_reach(tgt.value)
            if hit is not None:
                _n, _r, chain = hit
                self._flag("PROTO005", tgt.value, chain,
                           "subscript store")
                return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._visit_store_target(el)
            return
        self.visit(tgt)

    def visit_Call(self, node):
        # x._shared.append(...): mutator through a private fleet chain
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            hit = self._private_reach(f.value)
            if hit is not None:
                _n, _r, chain = hit
                self._flag("PROTO005", f.value, chain,
                           f".{f.attr}() mutator call")
                for arg in node.args:
                    self.visit(arg)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
        self.generic_visit(node)

    # -- read shape ---------------------------------------------------
    def visit_Attribute(self, node):
        hit = self._private_reach(node)
        if hit is not None:
            _n, _r, chain = hit
            self._flag("PROTO004", node, chain, "private-state read")
            return  # the chain is one event, not one per hop
        self.generic_visit(node)


def lint_file_concurrency(path: str, rel: Optional[str] = None,
                          source: Optional[str] = None) -> List[Finding]:
    """PROTO004/005 over one Python file.  Returns [] for unparsable
    files (the lint must never be the thing that fails)."""
    rel = rel or path
    if source is None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            return []
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError:
        return []
    lint = _ConcurrencyLint(rel)
    lint.visit(tree)
    return lint.findings


def lint_host_concurrency(root: str,
                          subdirs: Iterable[str] = ("easydist_tpu",
                                                    "examples"),
                          ) -> List[Finding]:
    """The PROTO004/005 lint over every .py file beneath
    `root/<subdir>` (repo-relative paths, so baselines travel)."""
    findings: List[Finding] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                findings.extend(lint_file_concurrency(full, rel=rel))
    return findings
