"""CLI for the analyzer driver: `python -m easydist_tpu.analyze`.

Exit status is the gate: 0 when every error-severity finding is
baselined (or none exist), 1 when NEW errors appear.  `EASYDIST_ANALYZE=0`
skips every layer and exits 0 (the kill switch must win over the gate).

Examples:
    python -m easydist_tpu.analyze --targets ast
    python -m easydist_tpu.analyze --targets protocol --json out.json
    python -m easydist_tpu.analyze --sarif analyze.sarif --json out.json
    python -m easydist_tpu.analyze --refresh-baseline
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m easydist_tpu.analyze",
        description="easydist-tpu static analyzer driver")
    parser.add_argument("--root", default=None,
                        help="repo root (default: the package's parent)")
    parser.add_argument("--targets", default="ast,presets,protocol",
                        help="comma list: ast,presets,protocol "
                             "(default all three)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: "
                             "<root>/analyze_baseline.json)")
    parser.add_argument("--refresh-baseline", action="store_true",
                        help="rewrite the baseline from this run's "
                             "error findings and exit 0")
    parser.add_argument("--sarif", default=None, metavar="FILE",
                        help="write a SARIF 2.1.0 artifact")
    parser.add_argument("--json", default=None, metavar="FILE",
                        dest="json_out", help="write the full JSON report")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass the incremental result cache")
    parser.add_argument("--cache-dir", default=None,
                        help="cache directory (default: "
                             "<compile_cache_dir>/analyze)")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    if args.root is None:
        # the package's parent directory is the repo root in-tree; cwd
        # otherwise
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        root = os.path.dirname(pkg)
        if not os.path.isdir(os.path.join(root, "easydist_tpu")):
            root = os.getcwd()
    else:
        root = os.path.abspath(args.root)
    baseline = args.baseline or os.path.join(root, "analyze_baseline.json")
    targets = tuple(t.strip() for t in args.targets.split(",") if t.strip())

    if "presets" in targets:
        # the presets target wants a multi-device virtual mesh; both env
        # knobs only matter before jax initializes, so set them here at
        # the CLI boundary (library callers control their own platform)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        if "xla_force_host_platform_device_count" not in os.environ.get(
                "XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")

    from easydist_tpu.analyze.driver import (export_sarif, run_driver,
                                             write_baseline)

    result = run_driver(root, targets=targets, baseline_path=baseline,
                        use_cache=not args.no_cache,
                        cache_dir=args.cache_dir)

    if args.refresh_baseline and not result.skipped:
        write_baseline(baseline, result.report.errors())
        if not args.quiet:
            print(f"baseline refreshed: {baseline} "
                  f"({len(result.report.errors())} error finding(s))")
        return 0

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(export_sarif(result.report.findings), f, indent=1)
            f.write("\n")
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(result.to_json(), f, indent=1)
            f.write("\n")

    if not args.quiet:
        if result.skipped:
            print("analyze: skipped (EASYDIST_ANALYZE=0)")
        else:
            c = result.report.counts()
            print(f"analyze[{','.join(result.targets)}]: "
                  f"{c['error']} error(s) ({len(result.new_errors)} new, "
                  f"{result.baselined} baselined), {c['warning']} "
                  f"warning(s), {result.suppressed} suppressed; "
                  f"{result.n_files} file(s), cache {result.cache_hits} "
                  f"hit / {result.cache_misses} miss, "
                  f"{result.wall_s:.1f}s")
            for name, st in sorted(result.protocol.items()):
                print(f"  protocol[{name}]: {st['states']} states, "
                      f"{st['transitions']} transitions "
                      f"({'exhausted' if st['exhausted'] else 'CEILING'}"
                      f", committed {st['committed']})")
            for f_ in result.new_errors[:20]:
                print(f"  NEW {f_}")
            for f_ in result.report.findings:
                if f_.severity != "error":
                    print(f"  {f_}")
    return 1 if result.new_errors else 0


if __name__ == "__main__":
    sys.exit(main())
