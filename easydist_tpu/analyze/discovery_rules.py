"""Layer 10: pruned-discovery auditor — representative-transfer soundness
(`easydist_tpu.jaxfront.discovery`).

The pruned discovery pipeline reuses one discovered rule across a whole
propagation group (and across compiles via the persistent rule cache).
Rules are dim-indexed, so a transfer is sound exactly when the member
could have discovered the same rule itself.  `rule_transferable` gates
every transfer up front; this layer re-audits the transfer log after the
trace so a gating bug surfaces as a finding instead of a miscompile:

  DISC001 (error)    a group/cache transfer instantiated a rule the
                     member's shapes cannot carry: the rule's shard space
                     has a different tensor-row count or per-row rank
                     than the member, a halo is as wide as (or wider
                     than) the member's shard along the halo'd dim, or a
                     size-sensitive rule (block-cyclic sharding, or a
                     priced composite "strategies" rule whose costs embed
                     absolute shapes) was transferred across non-identical
                     shapes.
  DISC002 (warning)  execution discovery ran for a primitive that has an
                     analytic preset — the preset declined the instance.
                     Not a soundness problem (discovery still produces a
                     correct rule), but the compile pays the probe
                     harness for an op the preset bank claims to cover;
                     emitted at the decline site in the interpreter, not
                     here, because the audit log only sees transfers.

Both rules audit plain data rows (the interpreter's transfer records), so
goldens are cheap fixtures — the same property every other late-layer
auditor in this package keeps.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

from easydist_tpu import config as edconfig

from .findings import Finding, make_finding

__all__ = ["audit_rule_transfer"]


def _rank(shape) -> int:
    return len(tuple(shape))


def audit_rule_transfer(records: Sequence[Dict[str, Any]],
                        node: str = "discovery") -> List[Finding]:
    """Audit representative->member rule transfers (DISC001).

    Each record is one transfer the interpreter performed:
      {"sig": member exact signature, "prim": primitive name,
       "rep_sig": representative signature (or "<cache>"),
       "rep_shapes": tensor shapes the rule was discovered on,
       "member_shapes": tensor shapes it was instantiated for,
       "rule": the rule dict ({"space", "recombines"} or
               {"strategies", ...})}
    """
    findings: List[Finding] = []
    nshards = max(int(edconfig.discovery_nshards), 1)

    for rec in records:
        sig = rec.get("sig", "?")
        prim = rec.get("prim", "?")
        rule = rec.get("rule") or {}
        rep_shapes = [tuple(s) for s in rec.get("rep_shapes", [])]
        member_shapes = [tuple(s) for s in rec.get("member_shapes", [])]
        where = f"{node}.{prim}"

        def bad(msg: str) -> None:
            findings.append(make_finding(
                "DISC001", where,
                f"{msg} (member {sig[:96]!r} <- rep "
                f"{rec.get('rep_sig', '?')[:96]!r})"))

        space = rule.get("space")
        if "strategies" in rule or space is None:
            # priced composite rules (and space-less fallbacks) embed
            # absolute shapes in their costs — exact-shape transfer only
            if member_shapes != rep_shapes:
                bad("size-sensitive rule transferred across non-identical "
                    "shapes")
            continue

        if len(space.table) != len(member_shapes):
            bad(f"rule space has {len(space.table)} tensor rows but the "
                f"member has {len(member_shapes)}")
            continue

        for t_idx, row in enumerate(space.table):
            mshape = member_shapes[t_idx]
            if len(row) != _rank(mshape):
                bad(f"rule row {t_idx} has rank {len(row)} but the member "
                    f"tensor has rank {_rank(mshape)}")
                break
            row_bad = False
            for dim_idx, d in enumerate(row):
                if d.block > 1 and member_shapes != rep_shapes:
                    bad(f"block-cyclic sharding (block={d.block}) "
                        f"transferred across non-identical shapes")
                    row_bad = True
                    break
                if d.halo is not None:
                    shard = mshape[dim_idx] // nshards
                    if d.halo.width >= max(shard, 1):
                        bad(f"halo width {d.halo.width} >= member shard "
                            f"size {shard} along dim {dim_idx}")
                        row_bad = True
                        break
            if row_bad:
                break

    return findings
