"""Analyzer driver: one entry point over the whole rule stack.

`python -m easydist_tpu.analyze` wraps the twelve analyze layers behind
a single CLI with the shared infrastructure the per-layer hooks never
had (the Automap argument: compile-time analysis scales only when the
machinery — suppressions, baselines, artifact export, caching — is
shared, arXiv:2112.02958):

* **targets** — `ast` runs the layer-11 host-code donation lint AND the
  layer-12 concurrency sanitizer (PROTO004/005) over `easydist_tpu/` +
  `examples/`; `presets` compiles a small auto-solved preset and runs
  the full `CompileResult.analyze()` stack (strategy, program lint,
  memory plan, donation pairs) over it; `protocol` exhaustively
  explores the four layer-12 protocol specs (health, router, resume,
  transport — analyze/modelcheck.py) and gates on PROTO001/002 plus
  committed state-count drift.  `bench.py --analyze` remains the
  heavyweight preset gate.
* **inline suppressions** — `# easydist: disable=ALIAS001` (comma list
  for several rules) on the flagged line silences a finding; a
  suppression that silences nothing is itself reported (DRV001) so
  stale escapes burn down instead of accreting.
* **baseline** — a committed JSON of finding fingerprints
  (`Finding.fingerprint()`: rule|path|node, message and line excluded
  so rewording and unrelated edits don't churn it).  Baselined findings
  still report but do not gate; NEW findings fail the run.
  `--refresh-baseline` rewrites the file from the current report.
* **SARIF + JSON export** — `--sarif`/`--json` emit CI artifacts
  (SARIF 2.1.0 minimal profile).
* **incremental cache** — results are cached under
  `<compile_cache_dir>/analyze/` keyed on (artifact content hash,
  rule-module version): per source file for the `ast` target, per
  package-source snapshot for `presets`.  A warm rerun on unchanged
  artifacts skips the lint/compile and replays the stored findings
  byte-identically; editing any rule module invalidates everything.

`EASYDIST_ANALYZE=0` skips every target (the driver reports
`skipped`); `EASYDIST_ANALYZE_RAISE` is irrelevant here — the driver
never raises, it exit-codes.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import (RULES, SEV_ERROR, AnalysisReport, Finding,
                       make_finding)

# bump when the driver's own semantics change in a way that must
# invalidate cached results (cache keys include it alongside the rule
# module hashes)
DRIVER_VERSION = 1

_SUPPRESS_RE = re.compile(r"#\s*easydist:\s*disable=([A-Za-z0-9_, ]+)")

# the modules whose source forms the "rule version" half of every cache
# key: any edit to a rule (or to this driver) re-runs every layer
_RULE_MODULE_FILES = (
    "findings.py", "alias_rules.py", "strategy_rules.py",
    "jaxpr_rules.py", "overlap_rules.py", "memory_rules.py",
    "schedule_rules.py", "resilience_rules.py", "serve_rules.py",
    "fleet_rules.py", "kv_rules.py", "reshard_rules.py", "sim_rules.py",
    "discovery_rules.py", "modelcheck.py", "protocol_rules.py",
    "driver.py",
)


def rule_version() -> str:
    """Content hash of every rule module + the driver itself."""
    h = hashlib.sha256(str(DRIVER_VERSION).encode())
    base = os.path.dirname(os.path.abspath(__file__))
    for name in _RULE_MODULE_FILES:
        path = os.path.join(base, name)
        try:
            with open(path, "rb") as f:
                h.update(name.encode())
                h.update(f.read())
        except OSError:
            h.update(f"missing:{name}".encode())
    return h.hexdigest()[:16]


def finding_to_dict(f: Finding) -> Dict[str, object]:
    return {"rule_id": f.rule_id, "severity": f.severity, "node": f.node,
            "message": f.message, "path": f.path, "line": f.line}


def finding_from_dict(d: Dict[str, object]) -> Finding:
    return Finding(str(d["rule_id"]), str(d["severity"]), str(d["node"]),
                   str(d["message"]), path=d.get("path"),
                   line=d.get("line"))


# ------------------------------------------------------------ suppressions


def collect_suppressions(source: str) -> Dict[int, Set[str]]:
    """{1-based line -> rule ids} for every `# easydist: disable=...`
    comment — real COMMENT tokens only (a docstring that *mentions* the
    syntax is not a suppression).  Unknown rule ids are kept (they still
    mark the suppression as present, and DRV001 will flag them as
    unused)."""
    import io
    import tokenize

    out: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            if rules:
                out.setdefault(tok.start[0], set()).update(rules)
    return out


def apply_suppressions(findings: List[Finding],
                       suppressions: Dict[int, Set[str]],
                       rel_path: str) -> Tuple[List[Finding], int]:
    """Drop findings whose (line, rule) is suppressed; append one DRV001
    per suppression entry that silenced nothing.  Returns
    (kept + DRV001 findings, n_suppressed)."""
    used: Set[Tuple[int, str]] = set()
    kept: List[Finding] = []
    n_suppressed = 0
    for f in findings:
        rules = suppressions.get(f.line or -1, ())
        if f.rule_id in rules:
            used.add((f.line, f.rule_id))
            n_suppressed += 1
        else:
            kept.append(f)
    for line, rules in sorted(suppressions.items()):
        for rule in sorted(rules):
            if (line, rule) not in used:
                kept.append(make_finding(
                    "DRV001", f"{rel_path}:{line}",
                    f"suppression for {rule} silences nothing on this "
                    f"line — remove it (stale escapes hide future "
                    f"regressions)", path=rel_path, line=line))
    return kept, n_suppressed


# ---------------------------------------------------------------- baseline


def load_baseline(path: Optional[str]) -> Set[str]:
    """Fingerprints from a committed baseline file; {} when absent."""
    return {str(e.get("fingerprint"))
            for e in load_baseline_entries(path)
            if e.get("fingerprint")}


def load_baseline_entries(path: Optional[str]) -> List[Dict[str, object]]:
    """The baseline file's raw entry list ([] when absent/corrupt) —
    the DRV002 stale-entry audit needs the context fields, not just the
    fingerprints."""
    if not path or not os.path.exists(path):
        return []
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("findings", [])
        return [e for e in entries if isinstance(e, dict)]
    except (OSError, ValueError, AttributeError, TypeError):
        return []


def stale_baseline_findings(baseline_path: Optional[str],
                            findings: Iterable[Finding]) -> List[Finding]:
    """One DRV002 warning per baseline entry whose fingerprint no
    longer matches ANY current finding — the debt was paid (or the code
    moved) and the escape now hides a future regression at the same
    coordinates.  `--refresh-baseline` prunes them."""
    entries = load_baseline_entries(baseline_path)
    if not entries:
        return []
    current = {f.fingerprint() for f in findings}
    out: List[Finding] = []
    for e in sorted(entries, key=lambda e: str(e.get("fingerprint", ""))):
        fp = str(e.get("fingerprint", ""))
        if not fp or fp in current:
            continue
        out.append(make_finding(
            "DRV002", f"baseline:{fp}",
            f"baseline entry {e.get('rule_id', '?')} at "
            f"{e.get('path') or e.get('node') or '?'} matches no "
            f"current finding — the finding was fixed or moved; run "
            f"--refresh-baseline to prune it"))
    return out


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    """Rewrite the baseline from the current (post-suppression) report.
    Entries keep human-readable context next to the fingerprint so a
    reviewer can see WHAT was baselined, and are sorted for stable
    diffs."""
    entries = sorted(
        ({"fingerprint": f.fingerprint(), "rule_id": f.rule_id,
          "path": f.path, "node": f.node, "message": f.message}
         for f in findings),
        key=lambda e: (e["fingerprint"], e["message"]))
    seen: Set[str] = set()
    unique = [e for e in entries
              if not (e["fingerprint"] in seen or seen.add(e["fingerprint"]))]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "comment":
                   "legacy analyzer findings; new findings gate. "
                   "Refresh: python -m easydist_tpu.analyze "
                   "--refresh-baseline (see README).",
                   "findings": unique}, f, indent=1, sort_keys=True)
        f.write("\n")


# ------------------------------------------------------------------- cache


class ResultCache:
    """Incremental result store: one JSON file per (unit key) under
    `<compile_cache_dir>/analyze/`.  Keys embed the artifact content
    hash AND the rule version, so both an artifact edit and a rule edit
    miss cleanly; stale entries are just dead files."""

    def __init__(self, cache_dir: Optional[str] = None,
                 enabled: bool = True):
        from easydist_tpu import config as edconfig

        self.dir = cache_dir or os.path.join(edconfig.compile_cache_dir,
                                             "analyze")
        self.enabled = enabled
        self.hits = 0
        self.misses = 0

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.json")

    def get(self, key: str) -> Optional[Dict[str, object]]:
        if not self.enabled:
            return None
        try:
            with open(self._path(key), "r", encoding="utf-8") as f:
                payload = json.load(f)
            self.hits += 1
            return payload
        except (OSError, ValueError):
            self.misses += 1
            return None

    def put(self, key: str, payload: Dict[str, object]) -> None:
        if not self.enabled:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self._path(key) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self._path(key))
        except OSError:  # a read-only cache dir must not break analysis
            pass


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:24]


# ----------------------------------------------------------------- targets


def run_ast_target(root: str, cache: ResultCache,
                   rules_ver: str) -> Tuple[List[Finding], int, int]:
    """Layer-11 donation lint + layer-12 concurrency sanitizer over the
    repo, file by file, each file's (post-suppression) result cached on
    its content hash.  Returns (findings, n_files, n_suppressed)."""
    from .alias_rules import lint_file_donation
    from .protocol_rules import lint_file_concurrency

    findings: List[Finding] = []
    n_files = 0
    n_suppressed = 0
    for sub in ("easydist_tpu", "examples"):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                try:
                    with open(full, "rb") as f:
                        raw = f.read()
                except OSError:
                    continue
                n_files += 1
                key = f"ast-{_sha(raw + rules_ver.encode())}"
                hit = cache.get(key)
                if hit is not None:
                    findings.extend(finding_from_dict(d)
                                    for d in hit["findings"])
                    n_suppressed += int(hit.get("suppressed", 0))
                    continue
                source = raw.decode("utf-8", errors="replace")
                raw_findings = lint_file_donation(full, rel=rel,
                                                  source=source)
                raw_findings += lint_file_concurrency(full, rel=rel,
                                                      source=source)
                kept, n_sup = apply_suppressions(
                    raw_findings, collect_suppressions(source), rel)
                cache.put(key, {"findings": [finding_to_dict(f)
                                             for f in kept],
                                "suppressed": n_sup})
                findings.extend(kept)
                n_suppressed += n_sup
    return findings, n_files, n_suppressed


def _package_hash(root: str) -> str:
    """Content hash of every .py under easydist_tpu/ — the `presets`
    target's artifact identity (unchanged source => identical compile
    => replay the cached report)."""
    h = hashlib.sha256()
    base = os.path.join(root, "easydist_tpu")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                full = os.path.join(dirpath, fn)
                try:
                    with open(full, "rb") as f:
                        h.update(os.path.relpath(full, root).encode())
                        h.update(f.read())
                except OSError:
                    pass
    return h.hexdigest()[:24]


def run_presets_target(root: str, cache: ResultCache,
                       rules_ver: str) -> List[Finding]:
    """Compile a small auto-solved MLP train step and run the full
    `CompileResult.analyze()` stack over it (layers 1-3 + the layer-11
    donation-pair audit ride the same report).  Cached on the package
    source hash: a warm rerun skips the solver+trace entirely."""
    key = f"preset-mlp-{_sha((_package_hash(root) + rules_ver).encode())}"
    hit = cache.get(key)
    if hit is not None:
        return [finding_from_dict(d) for d in hit["findings"]]

    import jax
    import jax.numpy as jnp

    from easydist_tpu.jaxfront import easydist_compile, make_device_mesh
    from easydist_tpu.models import mlp_apply, mlp_init

    n_dev = len(jax.devices())
    if n_dev >= 2 and n_dev % 2 == 0:
        mesh = make_device_mesh((n_dev // 2, 2), ("dp", "tp"))
    else:
        mesh = make_device_mesh((n_dev,), ("dp",))
    params = mlp_init(jax.random.PRNGKey(0), sizes=(64, 128, 64))
    x = jax.random.normal(jax.random.PRNGKey(1), (8 * max(1, n_dev), 64))
    y = jax.random.normal(jax.random.PRNGKey(2), (8 * max(1, n_dev), 64))

    def step(p, xb, yb):
        def loss_fn(p):
            return jnp.mean((mlp_apply(p, xb) - yb) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(
            lambda a, g: a - 0.05 * g, p, grads), loss

    compiled = easydist_compile(step, mesh=mesh, compile_only=True)
    compiled(params, x, y)
    report = compiled.analyze(raise_on_error=False, export=False)
    findings = [Finding(f.rule_id, f.severity, f.node, f.message,
                        path=getattr(f, "path", None),
                        line=getattr(f, "line", None))
                for f in report.findings]
    cache.put(key, {"findings": [finding_to_dict(f) for f in findings]})
    return findings


def run_protocol_target(cache: ResultCache, rules_ver: str,
                        ) -> Tuple[List[Finding],
                                   Dict[str, Dict[str, object]]]:
    """Layer-12a: exhaustively explore the four shipped protocol specs
    (analyze/modelcheck.py) at their committed scope.  Findings are
    PROTO001/002 from the explorer plus one PROTO002-severity-free
    budget check: a spec whose exhaustive state count drifts more than
    BUDGET_DRIFT_FRAC from its committed budget fails loudly (the spec
    changed shape without a conscious re-commit).  Cached on the rule
    version alone — the specs have no other input."""
    from .modelcheck import (ALL_SPECS, BUDGET_DRIFT_FRAC,
                             COMMITTED_STATES, audit_spec)

    key = f"protocol-{_sha(rules_ver.encode())}"
    hit = cache.get(key)
    if hit is not None:
        return ([finding_from_dict(d) for d in hit["findings"]],
                dict(hit["stats"]))

    findings: List[Finding] = []
    stats: Dict[str, Dict[str, object]] = {}
    for spec in ALL_SPECS():
        fs, res = audit_spec(spec)
        findings.extend(fs)
        stats[spec.name] = res.to_json()
        committed = COMMITTED_STATES.get(spec.name)
        if not res.exhausted:
            findings.append(make_finding(
                "PROTO002", f"protocol:{spec.name}",
                f"exploration hit the state ceiling at {res.states} "
                f"states without exhausting — the spec no longer "
                f"terminates at its committed scope"))
        elif committed is not None and abs(res.states - committed) \
                > BUDGET_DRIFT_FRAC * committed:
            findings.append(make_finding(
                "PROTO003", f"protocol:{spec.name}",
                f"exhaustive state count {res.states} drifted more "
                f"than {BUDGET_DRIFT_FRAC:.0%} from the committed "
                f"budget {committed} — re-commit COMMITTED_STATES "
                f"consciously if the spec change is intended"))
    cache.put(key, {"findings": [finding_to_dict(f) for f in findings],
                    "stats": stats})
    return findings, stats


def discovery_counters() -> Dict[str, object]:
    """The latest compile's pruned-discovery telemetry out of the PerfDB
    side-car (runtime/perfdb.py `record_discovery`), for the driver's
    `--json` report.  {} when the side-car is absent/empty — the
    counters are observability, never a gate."""
    try:
        from easydist_tpu.runtime.perfdb import PerfDB, discovery_db_path

        snap = PerfDB(path=discovery_db_path()).snapshot()
        traces = snap.get("discovery", {}).get("traces") or []
        if not traces:
            return {}
        return {"traces": len(traces), "latest": dict(traces[-1])}
    except Exception:
        return {}


# ------------------------------------------------------------------ driver


@dataclass
class DriverResult:
    report: AnalysisReport
    new_errors: List[Finding] = field(default_factory=list)
    baselined: int = 0
    suppressed: int = 0
    skipped: bool = False
    targets: Tuple[str, ...] = ()
    n_files: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    wall_s: float = 0.0
    # per-spec exploration stats from the `protocol` target ({} unless
    # it ran) and the pruned-discovery side-car counters
    protocol: Dict[str, Dict[str, object]] = field(default_factory=dict)
    discovery: Dict[str, object] = field(default_factory=dict)

    def to_json(self) -> Dict[str, object]:
        return {
            "skipped": self.skipped,
            "targets": list(self.targets),
            "counts": self.report.counts(),
            "rules": self.report.rule_counts(),
            "new_errors": [finding_to_dict(f) for f in self.new_errors],
            "baselined": self.baselined,
            "suppressed": self.suppressed,
            "n_files": self.n_files,
            "cache": {"hits": self.cache_hits,
                      "misses": self.cache_misses},
            "protocol": self.protocol,
            "discovery": self.discovery,
            "findings": [finding_to_dict(f)
                         for f in self.report.findings],
            "wall_s": round(self.wall_s, 3),
        }


def run_driver(root: str, targets: Iterable[str] = ("ast", "presets"),
               baseline_path: Optional[str] = None,
               use_cache: bool = True,
               cache_dir: Optional[str] = None) -> DriverResult:
    """Run the requested targets, apply the baseline, and return the
    aggregated result.  Never raises on findings — the exit decision
    (gate on `new_errors`) belongs to the caller."""
    from easydist_tpu import config as edconfig

    t0 = time.perf_counter()
    targets = tuple(targets)
    if not edconfig.enable_analyze:
        return DriverResult(report=AnalysisReport(), skipped=True,
                            targets=targets,
                            wall_s=time.perf_counter() - t0)
    cache = ResultCache(cache_dir=cache_dir, enabled=use_cache)
    rules_ver = rule_version()
    report = AnalysisReport()
    n_files = 0
    n_suppressed = 0
    protocol_stats: Dict[str, Dict[str, object]] = {}
    for target in targets:
        if target == "ast":
            fs, n_files, n_sup = run_ast_target(root, cache, rules_ver)
            report.extend(fs)
            n_suppressed += n_sup
        elif target == "presets":
            report.extend(run_presets_target(root, cache, rules_ver))
        elif target == "protocol":
            fs, protocol_stats = run_protocol_target(cache, rules_ver)
            report.extend(fs)
        else:
            raise ValueError(f"unknown analyze target {target!r} "
                             f"(expected 'ast', 'presets' or "
                             f"'protocol')")
    # stale-baseline audit BEFORE gating: DRV002 entries are warnings,
    # so they report without flipping the exit code
    report.extend(stale_baseline_findings(baseline_path,
                                          report.findings))
    baseline = load_baseline(baseline_path)
    errors = report.errors()
    new_errors = [f for f in errors if f.fingerprint() not in baseline]
    baselined = len(errors) - len(new_errors)
    return DriverResult(report=report, new_errors=new_errors,
                        baselined=baselined, suppressed=n_suppressed,
                        targets=targets, n_files=n_files,
                        cache_hits=cache.hits,
                        cache_misses=cache.misses,
                        wall_s=time.perf_counter() - t0,
                        protocol=protocol_stats,
                        discovery=discovery_counters())


# ------------------------------------------------------------------- SARIF

_SARIF_LEVEL = {"error": "error", "warning": "warning", "info": "note"}


def export_sarif(findings: Iterable[Finding]) -> Dict[str, object]:
    """Minimal SARIF 2.1.0 document over the findings (rule metadata
    from the registry; findings without source coordinates anchor to
    their artifact node in the message only)."""
    findings = list(findings)
    used_rules = sorted({f.rule_id for f in findings})
    results = []
    for f in findings:
        res: Dict[str, object] = {
            "ruleId": f.rule_id,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f"{f.node}: {f.message}"},
        }
        if f.path:
            res["locations"] = [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": int(f.line or 1)},
                }}]
        results.append(res)
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "easydist-analyze",
                "informationUri":
                    "https://github.com/alibaba/easydist",
                "rules": [{"id": rid,
                           "shortDescription": {"text": RULES[rid][1]},
                           "defaultConfiguration":
                               {"level": _SARIF_LEVEL.get(RULES[rid][0],
                                                          "warning")}}
                          for rid in used_rules],
            }},
            "results": results,
        }],
    }
