"""Analyze layer 4: resilience auditor.

Two static checks over the robustness layer's load-bearing invariants:

RES001 — guard trace parity.  The NaN-step guard is a strict opt-in: with
``step_guard`` off, a dp/zero builder MUST emit the same traced program as
a build that never heard of the guard (the skip-and-hold cond, the guard
state, everything must vanish — not just be "inactive").  The audit traces
two builds that claim to be equivalent and compares their jaxprs
literally; any drift means the guard leaked into the off path.

RES002/RES003 — checkpoint commit-protocol integrity.  A checkpoint root
is audited directory-by-directory: every COMMITTED checkpoint must pass
manifest verification (RES002 error — resuming from it would poison
training state), and dead write debris (.tmp_* dirs, torn step_N dirs
without a COMMITTED marker that a newer committed step supersedes) is
reported as RES003 so operators see GC lag before the disk fills.

Like every analyze layer, these return `Finding` lists; callers aggregate
into an `AnalysisReport` and gate on `edconfig.analyze_raise`.
"""

from __future__ import annotations

import os
import re
from typing import Callable, List, Sequence

import jax

from .findings import Finding, make_finding


def guard_off_jaxpr(step: Callable, example_args: Sequence) -> str:
    """Canonical trace string of a step build (the parity comparand)."""
    return str(jax.make_jaxpr(step)(*example_args))


def audit_guard_parity(step_a: Callable, step_b: Callable,
                       example_args: Sequence,
                       node: str = "step") -> List[Finding]:
    """RES001: `step_a` and `step_b` claim to emit the same program (e.g. a
    guard-off build vs a build predating the guard kwarg, or the
    knob-default build vs an explicit step_guard=False build).  Trace both
    and compare literally — jaxpr identity, not allclose."""
    ja = guard_off_jaxpr(step_a, example_args)
    jb = guard_off_jaxpr(step_b, example_args)
    if ja != jb:
        # find the first divergence for the message; dumping both programs
        # would drown the report
        n = next((i for i, (a, b) in enumerate(zip(ja, jb)) if a != b),
                 min(len(ja), len(jb)))
        return [make_finding(
            "RES001", node,
            f"guard-off traced programs differ (lengths {len(ja)} vs "
            f"{len(jb)}, first divergence at char {n}: "
            f"...{ja[max(0, n - 30):n + 30]!r}... vs "
            f"...{jb[max(0, n - 30):n + 30]!r}...)")]
    return []


def audit_checkpoint_root(path: str) -> List[Finding]:
    """RES002/RES003 over every entry of a checkpoint root directory."""
    from easydist_tpu.runtime.checkpoint import (COMMITTED_NAME,
                                                 verify_checkpoint)

    findings: List[Finding] = []
    try:
        entries = sorted(os.listdir(path))
    except FileNotFoundError:
        return findings

    committed_steps = []
    uncommitted = []
    for d in entries:
        m = re.fullmatch(r"step_(\d+)", d)
        if not m:
            continue
        full = os.path.join(path, d)
        if os.path.isfile(os.path.join(full, COMMITTED_NAME)):
            committed_steps.append((int(m.group(1)), full))
        else:
            uncommitted.append((int(m.group(1)), d))

    for step, full in committed_steps:
        problems = verify_checkpoint(full)
        for p in problems:
            findings.append(make_finding(
                "RES002", f"{path}/step_{step}", p))

    newest = max((s for s, _ in committed_steps), default=None)
    for step, d in uncommitted:
        if newest is not None and step <= newest:
            findings.append(make_finding(
                "RES003", f"{path}/{d}",
                f"torn uncommitted checkpoint superseded by committed "
                f"step {newest} (awaiting GC)"))
        else:
            findings.append(make_finding(
                "RES003", f"{path}/{d}",
                "uncommitted checkpoint with no newer committed step — a "
                "write died mid-commit; resume will use the previous "
                "committed step"))
    for d in entries:
        if d.startswith(".tmp_step_"):
            findings.append(make_finding(
                "RES003", f"{path}/{d}",
                "dead in-flight write directory (crash debris; GC'd by "
                "the next save once aged out)"))
    return findings
