"""`easydist_tpu.analyze`: static SPMD strategy & collective verifier.

A rule-based analyzer that runs after solving and before execution
(DistIR-style static checking over a typed distributed IR):

  layer 1  strategy verifier over solved MetaIR (`verify_axis`,
           `audit_solver_objective`) — placement typing, S-dim validity,
           PARTIAL resolution, solver objective audit;
  layer 2  collective-program linter over emitted jaxprs and comm plans
           (`lint_jaxpr`, `lint_fn`, `lint_bucket_plan`) — axis
           existence, cond-branch deadlock shapes, bucket tiling, int8
           accumulation.

Surfaced via `CompiledFunction.analyze()`, `bench.py --analyze`, and the
dryrun gate; findings export through the runtime PerfDB under
`("analyze_stats", <sub_key>)`.  Error-severity findings raise by default
(`EASYDIST_ANALYZE_RAISE=0` is the escape hatch); rule catalog in
docs/ANALYZE.md.
"""

from __future__ import annotations

import logging

from .findings import (RULES, AnalysisError, AnalysisReport, Finding,
                       make_finding)
from .jaxpr_rules import lint_bucket_plan, lint_fn, lint_jaxpr
from .strategy_rules import audit_solver_objective, verify_axis

logger = logging.getLogger(__name__)

__all__ = [
    "RULES", "AnalysisError", "AnalysisReport", "Finding", "make_finding",
    "lint_bucket_plan", "lint_fn", "lint_jaxpr",
    "audit_solver_objective", "verify_axis", "check_bucket_plan",
]


def check_bucket_plan(leaves, buckets) -> None:
    """Trace-time self-check hook for `comm.bucketer`: lint the plan and
    raise (or log, with the escape hatch) on error findings."""
    from easydist_tpu import config as edconfig

    findings = lint_bucket_plan(leaves, buckets)
    if not findings:
        return
    report = AnalysisReport(findings)
    if edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
