"""`easydist_tpu.analyze`: static SPMD strategy, collective, memory &
schedule verifier.

A rule-based analyzer that runs after solving and before execution
(DistIR-style static checking over a typed distributed IR):

  layer 1  strategy verifier over solved MetaIR (`verify_axis`,
           `audit_solver_objective`) — placement typing, S-dim validity,
           PARTIAL resolution, solver objective audit;
  layer 2  collective-program linter over emitted jaxprs and comm plans
           (`lint_jaxpr`, `lint_fn`, `lint_bucket_plan`) — axis
           existence, cond-branch deadlock shapes, bucket tiling, int8
           accumulation;
  layer 3  memory-plan & pipeline-schedule verifier
           (`verify_memory_plan`, `check_hbm_budget`, `audit_remat_plan`,
           `verify_schedule_tables`) — independent liveness/sizing audit
           of the graph memory plan, skyline soundness, the MEM004 HBM
           budget gate with its remat advisory, the remat-rewrite audit,
           and deadlock/stash-bound/bubble checks over pipeline tick
           schedules;
  layer 4  resilience auditor (`audit_guard_parity`,
           `audit_checkpoint_root`) — guard-off jaxpr parity (RES001) and
           checkpoint commit-protocol integrity over a checkpoint root
           (RES002 corrupt COMMITTED, RES003 stale debris);
  layer 5  serving auditor (`audit_decode_donation`,
           `audit_chunked_prefill`, `audit_prefix_cache`) — the SERVE001
           decode-step KV-cache donation lint (a non-donated cache turns
           every generated token into a full-cache HBM copy) and the
           SERVE002 chunked-prefill contract lint (staging donation,
           length-masked attention over the full bucket window so stale
           cache rows cannot leak into live logits, prefix-trie
           refcount/byte-accounting integrity) and the SERVE003
           speculative-rewind contract lint
           (`audit_speculative_rewind`: verify-step length masking,
           accept-walk bookkeeping never past the first mismatch,
           rollback leaves no table row on a released page);
  layer 7  paged-KV auditor (`audit_page_table`) — KV001 cross-checks
           the paged decode cache's host bookkeeping (kv/pool.py page
           refcounts, kv/table.py slot->page tables, prefix-trie page
           references): a freed page under a live table entry, a page
           with more holders than refcount, double frees, leaked pages,
           or byte-conservation drift all mean one sequence silently
           reads or reuses another's K/V;
  layer 6  fleet auditor (`audit_routing`, `audit_page_handoff`,
           `audit_drained_session`) — multi-replica serving hygiene:
           FLEET001 routing into a tripped-breaker/draining replica,
           FLEET002 KV page handoffs whose payload disagrees with the
           sha256 manifest, FLEET004 dispatch to a DEAD replica,
           FLEET005 resume descriptors that would break bitwise
           recovery, FLEET003 orphaned pinned trie pages left
           behind by a drain;
  layer 8  redistribution auditor (`audit_reshard_plan`,
           `audit_restored_state`) — RESHARD001 a chunked
           redistribution plan whose peak live bytes exceed the
           O(max(src_shard, dst_shard) + chunk) bound (silent
           degeneration to global materialization — the elastic-restore
           OOM), RESHARD002 a restored leaf whose sharding disagrees
           with the restore template's spec;
  layer 9  simulator/autoscaler auditor (`audit_prediction`,
           `audit_scale_decisions`, analyze/sim_rules.py) — SIM001 a
           simulator prediction whose relative error against a measured
           bench actual exceeds the committed bound
           (sim.simulate.SIM_REL_ERROR_BOUND) — the capacity planner
           and autoscaler would steer the fleet on numbers the hardware
           no longer agrees with; SIM002 autoscaler flap — opposite-
           direction scale actuations inside the hysteresis window (an
           A-B-A oscillation), each reversal paying a drain +
           page-migration + spin-up round trip for nothing;
  layer 10 pruned-discovery auditor (`audit_rule_transfer`,
           analyze/discovery_rules.py) — DISC001 a propagation-group or
           rule-cache transfer that instantiated a representative rule
           the member's shapes cannot carry (row/rank mismatch, halo
           wider than a member shard, size-sensitive rule across
           non-identical shapes); DISC002 execution discovery firing for
           a primitive whose analytic preset declined the instance;
  layer 11 donation/aliasing sanitizer (`audit_jaxpr_donation`,
           `audit_donation_pairs`, `audit_host_aliases`,
           `lint_host_donation`, analyze/alias_rules.py) — tier-1 runs
           JAX_PLATFORMS=cpu where JAX silently IGNORES buffer
           donation, so a use-after-donate passes every CPU test
           bitwise and corrupts HBM on real TPUs: ALIAS001 a donated
           invar read after its consuming dispatch (jaxpr form and the
           `ast` host-code lint over retained Python references),
           ALIAS002 one buffer donated through two positions / two
           state outputs claiming one donated input, ALIAS003 a
           donation XLA cannot honor (shape/dtype mismatch with every
           output — the silent-copy case), ALIAS004 a donated device
           buffer still reachable from a live host reference across a
           step boundary (snapshots, hot-page exports, trie-held rows);
  layer 12 fleet protocol model checker + concurrency sanitizer
           (`audit_spec`, `check_protocol_specs`,
           `check_protocol_conformance`, analyze/modelcheck.py +
           analyze/protocol_rules.py) — an explicit-state explorer over
           deterministic specs of the four fleet protocols
           (HealthMonitor ALIVE/SUSPECT/DEAD, FleetRouter
           drain/handoff/failover, ResumeDescriptor token-position
           commit, KVTransport chunked idempotent retry) enumerating
           EVERY interleaving of crash/duplicate/reorder/stall at small
           committed scope: PROTO001 a safety violation (false DEAD,
           double completion, double-commit) with the shortest
           counterexample trace attached, PROTO002 a reachable stuck
           state from which the goal is unreachable, PROTO003 drift
           between a live component's recorded `transitions()` stream
           (fleet/elastic drill logs replayed in CI) and the spec's
           admitted behavior; plus the host-code concurrency lint —
           PROTO004 a read of private fleet state across an object
           boundary, PROTO005 a mutation of a shared fleet structure
           outside its owning class (observers must consume snapshot
           surfaces; single-writer is what keeps the specs faithful);
  layer 13 quantized/tiered-KV sanitizer (`audit_quant_arena`,
           `audit_quant_program`, `audit_tier_roundtrip`,
           analyze/kv_quant_rules.py) — KVQ001 a block-scaled int8
           arena whose scale leaves are missing, mis-typed, or do not
           block-partition their payload (dequant would broadcast the
           wrong scales, bitwise-silently), KVQ002 a compiled paged
           step feeding int8 K/V into a `dot_general` without the
           dequant convert (logits off by the per-block scale), KVQ003
           a host-tier entry whose stored bytes fail their sha256
           manifest or whose byte accounting drifted (promotion would
           serve corrupt K/V).

Surfaced via `CompiledFunction.analyze()`, `bench.py --analyze`, the
dryrun gate, and the analyzer driver (`python -m easydist_tpu.analyze`:
inline suppressions, committed baseline, SARIF/JSON export, incremental
result cache — analyze/driver.py); findings export through the runtime
PerfDB under `("analyze_stats", <sub_key>)`.  Error-severity findings
raise by default (`EASYDIST_ANALYZE_RAISE=0` is the escape hatch;
`EASYDIST_ANALYZE=0` skips every layer); rule catalog in
docs/ANALYZE.md.
"""

from __future__ import annotations

import logging

from .alias_rules import (audit_donation_pairs, audit_host_aliases,
                          audit_jaxpr_donation, lint_file_donation,
                          lint_host_donation)
from .findings import (LAYERS, RULES, SEV_INFO, AnalysisError,
                       AnalysisReport, Finding, layer_of, make_finding,
                       rule_index_rows)
from .fleet_rules import (audit_drained_session, audit_page_handoff,
                          audit_resume, audit_routing)
from .jaxpr_rules import lint_bucket_plan, lint_fn, lint_jaxpr
from .kv_quant_rules import (audit_quant_arena, audit_quant_program,
                             audit_tier_roundtrip)
from .kv_rules import audit_page_table
from .modelcheck import (ALL_SPECS, COMMITTED_STATES, HealthSpec,
                         ResumeSpec, RouterSpec, Spec, TransportSpec,
                         audit_spec, explore, replay_health_events,
                         replay_restore_attempts,
                         replay_router_protocol,
                         replay_transport_commits)
from .protocol_rules import lint_file_concurrency, lint_host_concurrency
from .memory_rules import (audit_remat_plan, check_hbm_budget,
                           recompute_liveness, remat_advisory,
                           resolve_hbm_budget, verify_memory_plan)
from .overlap_rules import (lint_overlap_fn, lint_overlap_jaxpr,
                            lint_overlap_plan)
from .discovery_rules import audit_rule_transfer
from .reshard_rules import audit_reshard_plan, audit_restored_state
from .resilience_rules import (audit_checkpoint_root, audit_guard_parity,
                               guard_off_jaxpr)
from .schedule_rules import (gpipe_schedule_tables, schedule_stats,
                             verify_schedule_tables)
from .serve_rules import (audit_chunked_prefill, audit_decode_donation,
                          audit_prefix_cache, audit_speculative_rewind)
from .sim_rules import audit_prediction, audit_scale_decisions
from .strategy_rules import audit_solver_objective, verify_axis

logger = logging.getLogger(__name__)

__all__ = [
    "RULES", "AnalysisError", "AnalysisReport", "Finding", "make_finding",
    "lint_bucket_plan", "lint_fn", "lint_jaxpr",
    "audit_solver_objective", "verify_axis", "check_bucket_plan",
    "verify_memory_plan", "check_hbm_budget", "audit_remat_plan",
    "recompute_liveness", "remat_advisory", "resolve_hbm_budget",
    "verify_schedule_tables", "gpipe_schedule_tables", "schedule_stats",
    "check_schedule_tables",
    "lint_overlap_plan", "lint_overlap_jaxpr", "lint_overlap_fn",
    "check_overlap_plan",
    "audit_guard_parity", "audit_checkpoint_root", "guard_off_jaxpr",
    "audit_decode_donation", "check_decode_donation",
    "audit_chunked_prefill", "audit_prefix_cache",
    "check_chunked_prefill", "check_prefix_cache",
    "audit_speculative_rewind", "check_speculative_rewind",
    "audit_routing", "audit_page_handoff", "audit_drained_session",
    "audit_resume",
    "check_fleet_routing", "check_page_handoff", "check_fleet_drain",
    "check_resume_descriptor",
    "audit_page_table", "check_page_table",
    "audit_quant_arena", "audit_quant_program", "audit_tier_roundtrip",
    "check_quant_arena", "check_quant_program", "check_tier_roundtrip",
    "audit_reshard_plan", "audit_restored_state",
    "check_reshard_plan", "check_restored_state",
    "audit_prediction", "audit_scale_decisions",
    "check_sim_prediction", "check_sim_autoscale",
    "audit_rule_transfer",
    "audit_jaxpr_donation", "audit_donation_pairs",
    "audit_host_aliases", "lint_host_donation", "lint_file_donation",
    "check_donation_pairs", "check_host_aliases",
    "Spec", "HealthSpec", "RouterSpec", "ResumeSpec", "TransportSpec",
    "ALL_SPECS", "COMMITTED_STATES", "explore", "audit_spec",
    "replay_health_events", "replay_router_protocol",
    "replay_transport_commits", "replay_restore_attempts",
    "lint_file_concurrency", "lint_host_concurrency",
    "check_protocol_specs", "check_protocol_conformance",
    "LAYERS", "layer_of", "rule_index_rows",
]


def _enabled() -> bool:
    """The layer kill switch (EASYDIST_ANALYZE=0): every check_* hook
    returns empty without computing anything when analysis is off."""
    from easydist_tpu import config as edconfig

    return edconfig.enable_analyze


def check_bucket_plan(leaves, buckets) -> None:
    """Trace-time self-check hook for `comm.bucketer`: lint the plan and
    raise (or log, with the escape hatch) on error findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return
    findings = lint_bucket_plan(leaves, buckets)
    if not findings:
        return
    report = AnalysisReport(findings)
    if edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)


def check_overlap_plan(leaves, order, buckets=None) -> None:
    """Trace-time self-check hook for `comm.overlap`: validate the
    emission-order permutation and the reordered bucket plan, raising (or
    logging, with the escape hatch) on error findings.  `leaves` are the
    ORDERED leaves when `buckets` is given."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return
    findings = lint_overlap_plan(leaves, order, buckets)
    if not findings:
        return
    report = AnalysisReport(findings)
    if edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)


def check_schedule_tables(tables, n_stages: int, n_virtual: int,
                          n_microbatches: int, fwd_only: bool = False,
                          node: str = "pipeline") -> None:
    """Build-time self-check hook for the pipeline schedule builders
    (`parallel/pipeline.py`, `parallel/auto_pipeline.py`): verify the tick
    tables and raise (or log, with the escape hatch) on error findings.
    Warning/info findings (the SCHED003 bubble report) only log."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return
    findings = verify_schedule_tables(tables, n_stages, n_virtual,
                                      n_microbatches, fwd_only=fwd_only,
                                      node=node)
    if not findings:
        return
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.log(logging.INFO if f.severity == SEV_INFO
                   else logging.WARNING, "[analyze] %s", f)


def check_decode_donation(result, cache_arg: int = 0,
                          node: str = "decode"):
    """Compile-time self-check hook for `serve.generation`: audit the
    compiled decode step's cache donation (SERVE001, warning severity —
    logs, never raises; a non-donated cache is slow, not wrong).
    Returns the findings so callers/tests can assert on them."""
    if not _enabled():
        return []
    findings = audit_decode_donation(result, cache_arg=cache_arg,
                                     node=node)
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_chunked_prefill(result, cache_arg: int = 0,
                          node: str = "prefill_chunk"):
    """Compile-time self-check hook for the chunked-prefill scheduler:
    audit staging donation (warning — slow) and the length-mask (error —
    stale-row leakage).  Error findings raise under `analyze_raise`
    (missing mask means WRONG tokens, not slow ones); warnings log.
    Returns the findings so callers/tests can assert on them."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_chunked_prefill(result, cache_arg=cache_arg,
                                     node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_speculative_rewind(result=None, *, cache_arg: int = 0,
                             node: str = "verify", draft=None,
                             target=None, n_accepted=None, pool=None,
                             table=None, trie=None):
    """Self-check hook for speculative decoding (SERVE003), called by
    `serve.generation` at each artifact's natural checkpoint: the
    compiled verify step once per signature (`result` — donation warns,
    a missing length mask errors), the accept-walk bookkeeping every
    commit (`draft`/`target`/`n_accepted` — advancing past the first
    mismatch errors), and the paged page table after every rollback that
    released pages (`pool`/`table` — a dangling released page errors).
    Error findings raise under `analyze_raise`; warnings log.  Returns
    the findings so callers/tests can assert on them."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_speculative_rewind(
        result, cache_arg=cache_arg, node=node, draft=draft,
        target=target, n_accepted=n_accepted, pool=pool, table=table,
        trie=trie)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_prefix_cache(trie, node: str = "prefix_cache"):
    """Runtime self-check hook for the prefix trie: refcount/byte
    accounting invariants (SERVE002).  Drift raises under
    `analyze_raise` — eviction over corrupt bookkeeping could free a
    pinned chunk under a live slot.  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_prefix_cache(trie, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_page_table(pool, table, trie=None, node: str = "kv"):
    """Runtime self-check hook for the paged KV session (KV001): audit
    the page pool / page table / prefix-trie bookkeeping against each
    other and raise (or log, with the escape hatch) on error findings —
    serving on corrupt page accounting reads or frees another sequence's
    K/V, bitwise-silently.  Returns the findings so callers/tests can
    assert on them."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_page_table(pool, table, trie=trie, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_quant_arena(arena, node: str = "kv.quant"):
    """Runtime self-check hook for the quantized paged arena (KVQ001):
    payload/scale structural consistency.  Raises (or logs, with the
    escape hatch) on error findings — a desynced scale arena
    dequantizes pages into garbage, bitwise-silently.  Returns the
    findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_quant_arena(arena, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_quant_program(result, node: str = "decode.quant"):
    """Compile-time self-check hook for quantized paged steps (KVQ002):
    lint the program for int8 operands reaching a dot_general (the
    missing-dequant bug).  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_quant_program(result, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_tier_roundtrip(tier, node: str = "kv.tier"):
    """Runtime self-check hook for the host KV tier (KVQ003): manifest
    re-verification + byte accounting over every stored entry.  Returns
    the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_tier_roundtrip(tier, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_fleet_routing(decisions, node: str = "fleet"):
    """Audit hook for a fleet router's decision log: FLEET001 (routed to
    a tripped-breaker or draining replica) raises under `analyze_raise`.
    Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_routing(decisions, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_page_handoff(manifest, path, node: str = "handoff"):
    """Transfer-time self-check hook for `fleet.transport`: FLEET002
    (payload disagrees with the sha256 manifest) raises under
    `analyze_raise` — committing a corrupt page poisons every request
    sharing the prefix.  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_page_handoff(manifest, path, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_fleet_drain(session, node: str = "drain"):
    """Drain-time self-check hook for the fleet router: FLEET003
    (orphaned pinned pages / trie bookkeeping drift on a drained
    session) — warning severity, logs and returns the findings."""
    if not _enabled():
        return []
    findings = audit_drained_session(session, node=node)
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_reshard_plan(plan, node: str = "reshard"):
    """Plan-time self-check hook for `easydist_tpu.reshard`: RESHARD001
    (peak live bytes over the chunked bound) raises under
    `analyze_raise` BEFORE any byte moves — a degenerate plan at model
    scale is the restore OOM, so it must fail at planning, not on the
    device.  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_reshard_plan(plan, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_restored_state(restored, template, node: str = "restore"):
    """Post-restore self-check hook for `runtime.checkpoint`: RESHARD002
    (a restored leaf's sharding disagrees with the template spec) raises
    under `analyze_raise` — training on a silently re-laid-out state
    works but pays n_devices x memory and a re-shard collective every
    step.  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_restored_state(restored, template, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_resume_descriptor(descriptor, resume_prompt=None,
                            node: str = "resume"):
    """Resume-time self-check hook for the fleet failover path: FLEET005
    (descriptor disagrees with the original request — prefix mismatch,
    budget overrun, or eos already emitted) raises under `analyze_raise`
    BEFORE the resubmit, so a recovery that would silently change tokens
    fails loudly instead.  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_resume(descriptor, resume_prompt, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_sim_prediction(rows, bound=None, node: str = "sim"):
    """Validation hook for `bench.py --simulate`: SIM001 (a prediction
    row's relative error exceeds the committed bound) raises under
    `analyze_raise` — a fleet steered on drifted predictions is the
    failure the simulator gate exists to catch.  Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_prediction(rows, bound=bound, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_sim_autoscale(decisions, window=None, node: str = "autoscale"):
    """Post-drill hook for `bench.py --autoscale`: SIM002 (opposite
    scale actuations inside the hysteresis window — an A-B-A flap)
    raises under `analyze_raise` over the autoscaler's decision log.
    Returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_scale_decisions(decisions, window=window, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_donation_pairs(result, node: str = "state-io"):
    """Compile-time self-check hook for the layer-11 donation-pair
    audit (ALIAS002 two outputs claiming one donated input, ALIAS003 a
    declared donation XLA cannot honor — the silent-copy case).  Error
    findings raise under `analyze_raise`; returns the findings so
    callers/tests can assert on them."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_donation_pairs(result, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_protocol_specs(specs=None, max_states: int = None,
                         node: str = None):
    """Layer-12a self-check hook: exhaustively explore the protocol
    specs (default: the four shipped fleet protocols at committed
    scope) and convert violations to findings — PROTO001 a safety
    violation with the shortest counterexample interleaving, PROTO002 a
    reachable stuck state.  Error findings raise under `analyze_raise`;
    returns the findings so callers/tests can assert on them."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    from .modelcheck import MAX_STATES_DEFAULT

    findings = []
    for spec in (specs if specs is not None else ALL_SPECS()):
        fs, _res = audit_spec(
            spec, node=node,
            max_states=max_states or MAX_STATES_DEFAULT)
        findings.extend(fs)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_protocol_conformance(router=None, health=None, transport=None,
                               restore_attempts=None,
                               node: str = "drill"):
    """Layer-12b conformance hook: replay live components' recorded
    `transitions()` streams (and an elastic restore's attempt trail)
    through the spec automata — PROTO003 fires on any event the spec
    does not admit (a dropped completion, an illegal health edge, a
    double KV commit, a restore halving that skipped a step).  The
    fleet/elastic chaos drills call this after every run, so every CI
    drill log doubles as a conformance trace.  Error findings raise
    under `analyze_raise`; returns the findings."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = []
    if router is not None:
        findings.extend(replay_router_protocol(
            router.transitions(), node=f"{node}:router"))
    if health is not None:
        findings.extend(replay_health_events(
            health.transitions(), node=f"{node}:health"))
    if transport is not None:
        findings.extend(replay_transport_commits(
            transport.transitions(), node=f"{node}:transport"))
    if restore_attempts is not None:
        findings.extend(replay_restore_attempts(
            restore_attempts, node=f"{node}:restore"))
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings


def check_host_aliases(donated, holders, node: str = "session"):
    """Step-boundary self-check hook for `serve.generation` (ALIAS004):
    identity overlap between the buffers the next dispatch donates
    (cache/staging/arena) and host-held references that outlive the
    step (snapshots, hot-page exports, trie-held rows).  Error findings
    raise under `analyze_raise`; returns the findings so callers/tests
    can assert on them."""
    from easydist_tpu import config as edconfig

    if not edconfig.enable_analyze:
        return []
    findings = audit_host_aliases(donated, holders, node=node)
    report = AnalysisReport(findings)
    if report.errors() and edconfig.analyze_raise:
        report.raise_on_errors()
    for f in findings:
        logger.warning("[analyze] %s", f)
    return findings
