"""Layer 5: serving auditor.

SERVE001 — the decode-step cache-donation lint.  The whole economics of
token-level serving (serve/generation.py) rests on the KV cache pool
being updated *in place* by XLA: a decode step's cost is one row write
plus attention reads.  If the cache input is not donated, every token
instead pays a full copy of layers x slots x bucket x dim bytes on the
cache update — correct, silent, and catastrophically slow.  This audit
checks the compiled decode step's donation vector covers every leaf of
the cache argument, so the regression is caught at compile time rather
than in a latency dashboard.

SERVE003 — the speculative-rewind contract lint.  Speculative decoding
(serve/speculate.py + the verify steps in models/*.py) is only a pure
speed knob while three properties hold: (a) the verify program's
attention is LENGTH-MASKED to `key_pos <= query_pos` — the k+1 verify
rows it writes sit above the committed positions, and position i's
logits must not see rows > i, or a rejected draft would contaminate the
very logits that judge it; (b) the host-side accept walk never commits
past the first draft/target mismatch — one token past it and the stream
silently diverges from plain greedy; (c) a paged rollback leaves no
table row pointing at a released spill page (delegated to KV001's
page-table audit, re-tagged so speculative findings are attributable).

SERVE002 — the chunked-prefill contract lint.  The prefix-reuse scheduler
(serve/generation.py + serve/prefix_cache.py) leans on three properties:
(a) the multi-row staging cache is donated to every chunk call (same
economics as SERVE001, but per chunk); (b) the chunk program's attention
over the full bucket window is LENGTH-MASKED — a `select` whose predicate
compares against an `iota` over key positions — because staging rows
carry restored prefixes, stale tails from recycled rows, and idle-row
garbage, and only the mask keeps them out of live logits (a missing mask
is *wrong*, not slow, hence error severity); (c) the prefix trie's
refcount/byte accounting stays consistent (`audit_prefix_cache`).
"""

from __future__ import annotations

from typing import List

from .findings import SEV_WARNING, Finding, make_finding


def _arg_leaf_ranges(in_tree) -> List[tuple]:
    """[(start, stop)) flat-leaf index ranges of each positional arg in a
    CompileResult's input treedef (structured ((args...), {kwargs}))."""
    args_tree = in_tree.children()[0]
    ranges = []
    base = 0
    for child in args_tree.children():
        n = child.num_leaves
        ranges.append((base, base + n))
        base += n
    return ranges


def audit_decode_donation(result, cache_arg: int = 0,
                          node: str = "decode") -> List[Finding]:
    """SERVE001: verify every flat leaf of positional arg `cache_arg` is
    in `result.donated_invars`.  Non-donated leaves aggregate into ONE
    finding (one decode step, one verdict); returns [] when the cache is
    fully donated."""
    return _donation_findings(
        result, cache_arg, node, "SERVE001",
        "the decode step will copy the full KV cache every token "
        "(donate_state/enable_donation off, or the cache is not "
        "threaded as a paired state output)")


def _donation_findings(result, cache_arg: int, node: str,
                       rule_id: str, what: str,
                       severity=None) -> List[Finding]:
    """Shared donation walk for SERVE001/SERVE002: every flat leaf of
    positional arg `cache_arg` must be in `result.donated_invars`."""
    ranges = _arg_leaf_ranges(result.in_tree)
    if cache_arg >= len(ranges):
        return [make_finding(
            rule_id, node,
            f"cache arg index {cache_arg} out of range: the compiled "
            f"step has {len(ranges)} positional args", severity=severity)]
    start, stop = ranges[cache_arg]
    donated = set(getattr(result, "donated_invars", ()) or ())
    missing = [i for i in range(start, stop) if i not in donated]
    if not missing:
        return []
    return [make_finding(
        rule_id, node,
        f"{len(missing)}/{stop - start} cache leaves (flat input indices "
        f"{missing[:8]}{'...' if len(missing) > 8 else ''}) are not "
        f"donated; {what}", severity=severity)]


_COMPARE_PRIMS = {"le", "lt", "ge", "gt", "eq", "ne"}
_SELECT_PRIMS = {"select_n", "select"}


def _has_masked_select(jaxpr, max_depth: int = 24) -> bool:
    """True iff some select's predicate derives (within `max_depth`
    producer hops) from a comparison with an `iota` ancestor — the
    `where(key_pos <= query_pos, scores, -inf)` shape the chunked-prefill
    attention must carry.  Recurses into sub-jaxprs (pjit/cond/scan)."""
    from jax._src import core as jex_core

    producer = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            producer[ov] = eqn

    def ancestry_prims(var, depth):
        seen = set()
        stack = [(var, depth)]
        prims = set()
        while stack:
            v, d = stack.pop()
            if d <= 0 or isinstance(v, jex_core.Literal):
                continue
            eqn = producer.get(v)
            if eqn is None or id(eqn) in seen:
                continue
            seen.add(id(eqn))
            prims.add(eqn.primitive.name)
            for iv in eqn.invars:
                stack.append((iv, d - 1))
        return prims

    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _SELECT_PRIMS:
            prims = ancestry_prims(eqn.invars[0], max_depth)
            if prims & _COMPARE_PRIMS and "iota" in prims:
                return True
    for eqn in jaxpr.eqns:
        for param in eqn.params.values():
            sub = []
            if hasattr(param, "jaxpr"):
                sub = [param.jaxpr]
            elif isinstance(param, (list, tuple)):
                sub = [p.jaxpr for p in param if hasattr(p, "jaxpr")]
            for s in sub:
                if _has_masked_select(s, max_depth):
                    return True
    return False


def audit_chunked_prefill(result, cache_arg: int = 0,
                          node: str = "prefill_chunk") -> List[Finding]:
    """SERVE002 over a compiled chunked-prefill step: (a) the staging
    cache (positional arg `cache_arg`) must be fully donated — warning
    severity, slow-not-wrong; (b) the program must contain a length-masked
    select over an iota-derived predicate — error severity, because an
    unmasked full-window attention lets restored-prefix tails, recycled-
    row garbage, and idle-row writes leak into live rows' logits.  The
    mask walk retraces `result.jitted` on its input avals; when the
    retrace is unavailable the mask check is skipped (donation still
    audits)."""
    findings = _donation_findings(
        result, cache_arg, node, "SERVE002",
        "every prefill chunk pays a full staging-cache HBM copy instead "
        "of an in-place XLA update", severity=SEV_WARNING)
    try:
        import jax

        traced = jax.make_jaxpr(result.jitted)(*result.in_avals)
    except Exception:
        return findings
    if not _has_masked_select(traced.jaxpr):
        findings.append(make_finding(
            "SERVE002", node,
            "no length-masked select found in the chunked-prefill "
            "program: the attention window over the staging cache is not "
            "masked to `key_pos <= query_pos`, so stale rows (restored "
            "prefix tails, recycled staging rows, idle-row garbage) can "
            "leak into live logits"))
    return findings


def audit_speculative_rewind(result=None, *, cache_arg: int = 0,
                             node: str = "verify",
                             draft=None, target=None,
                             n_accepted: int = None,
                             pool=None, table=None,
                             trie=None) -> List[Finding]:
    """SERVE003 over whichever speculative artifact is supplied (the
    three arms compose — pass any subset):

    * `result` (a compiled verify step): the cache/arena (positional arg
      `cache_arg`) must be donated (warning — slow, not wrong) and the
      program must carry a length-masked select over an iota-derived
      predicate (error — without `key_pos <= query_pos`, the speculative
      rows the step itself writes above the committed positions leak
      into the logits that decide acceptance, and rejected drafts
      contaminate their own verdict).
    * `draft`/`target`/`n_accepted` (one slot's accept-walk bookkeeping,
      token id sequences + the accepted-draft count): `n_accepted` must
      not exceed the longest matching prefix of draft and target — one
      committed token past the first mismatch silently diverges the
      stream from plain greedy.
    * `pool`/`table` (a paged layout after rollback): the full KV001
      page-table/refcount audit, re-tagged SERVE003 — a rollback that
      released a spill page while a table row still points at it hands
      another sequence's K/V to this one's attention.
    """
    findings: List[Finding] = []
    if result is not None:
        findings.extend(_donation_findings(
            result, cache_arg, node, "SERVE003",
            "every verify step pays a full KV-cache HBM copy instead of "
            "an in-place XLA update", severity=SEV_WARNING))
        traced = None
        try:
            import jax

            traced = jax.make_jaxpr(result.jitted)(*result.in_avals)
        except Exception:
            pass
        if traced is not None and not _has_masked_select(traced.jaxpr):
            findings.append(make_finding(
                "SERVE003", node,
                "no length-masked select found in the verify program: "
                "attention is not masked to `key_pos <= query_pos`, so "
                "the speculative rows the step writes above the "
                "committed positions (including rejected drafts) leak "
                "into the logits that decide acceptance"))
    if draft is not None and target is not None and n_accepted is not None:
        match = 0
        for d, t in zip(draft, target):
            if int(d) != int(t):
                break
            match += 1
        if n_accepted > match:
            findings.append(make_finding(
                "SERVE003", node,
                f"accepted-prefix bookkeeping advanced past the first "
                f"draft/target mismatch: n_accepted={n_accepted} but "
                f"draft {list(map(int, draft))} matches target "
                f"{list(map(int, target))[:len(list(draft))]} only "
                f"through index {match} — the committed stream diverges "
                f"from plain greedy"))
    if pool is not None and table is not None:
        from .kv_rules import audit_page_table

        findings.extend(
            make_finding("SERVE003", node,
                         f"paged rollback left inconsistent "
                         f"page-table/refcount state: {f.message}")
            for f in audit_page_table(pool, table, trie=trie, node=node))
    return findings


def audit_prefix_cache(trie, node: str = "prefix_cache") -> List[Finding]:
    """SERVE002 over a live `serve.prefix_cache.PrefixCache`: one error
    finding per refcount/byte-accounting invariant violation (drift here
    means eviction decisions are being made on corrupt bookkeeping —
    a pinned chunk could be evicted under a live slot)."""
    return [make_finding("SERVE002", node, problem)
            for problem in trie.check_invariants()]
