"""Layer 5: serving auditor.

One rule so far: SERVE001 — the decode-step cache-donation lint.  The
whole economics of token-level serving (serve/generation.py) rests on the
KV cache pool being updated *in place* by XLA: a decode step's cost is
one row write plus attention reads.  If the cache input is not donated,
every token instead pays a full copy of layers x slots x bucket x dim
bytes on the cache update — correct, silent, and catastrophically slow.
This audit checks the compiled decode step's donation vector covers every
leaf of the cache argument, so the regression is caught at compile time
rather than in a latency dashboard.
"""

from __future__ import annotations

from typing import List

from .findings import Finding, make_finding


def _arg_leaf_ranges(in_tree) -> List[tuple]:
    """[(start, stop)) flat-leaf index ranges of each positional arg in a
    CompileResult's input treedef (structured ((args...), {kwargs}))."""
    args_tree = in_tree.children()[0]
    ranges = []
    base = 0
    for child in args_tree.children():
        n = child.num_leaves
        ranges.append((base, base + n))
        base += n
    return ranges


def audit_decode_donation(result, cache_arg: int = 0,
                          node: str = "decode") -> List[Finding]:
    """SERVE001: verify every flat leaf of positional arg `cache_arg` is
    in `result.donated_invars`.  Non-donated leaves aggregate into ONE
    finding (one decode step, one verdict); returns [] when the cache is
    fully donated."""
    ranges = _arg_leaf_ranges(result.in_tree)
    if cache_arg >= len(ranges):
        return [make_finding(
            "SERVE001", node,
            f"cache arg index {cache_arg} out of range: the compiled "
            f"step has {len(ranges)} positional args")]
    start, stop = ranges[cache_arg]
    donated = set(getattr(result, "donated_invars", ()) or ())
    missing = [i for i in range(start, stop) if i not in donated]
    if not missing:
        return []
    return [make_finding(
        "SERVE001", node,
        f"{len(missing)}/{stop - start} cache leaves (flat input indices "
        f"{missing[:8]}{'...' if len(missing) > 8 else ''}) are not "
        f"donated; the decode step will copy the full KV cache every "
        f"token (donate_state/enable_donation off, or the cache is not "
        f"threaded as a paired state output)")]
