"""Layer 2b: overlapped-flush verifier (the OVL rule family).

`comm.overlap` reorders gradient buckets into backward emission order and
chains their collectives through `optimization_barrier` tokens.  Two
things can silently go wrong with that transformation, and both are
statically checkable:

  OVL001  the emission order is not a permutation of the flat leaves —
          a reordered flush would then drop some gradients and duplicate
          others at unpack;
  OVL002  the barrier token chain is broken: two consecutive reducing
          collectives in the flush have NO ordering dependency (neither a
          barrier token nor a data dependence), so XLA is free to clump
          them back into one post-backward group and the overlap is lost.

OVL002 is scoped to ISOLATED flush programs (a traced
`overlapped_reduce_gradients` / `chain_leaf_reduces` call): a whole train
step legitimately contains unchained collectives (the loss pmean), so
linting it here would be all false positives — whole-program collective
linting stays with layer 2 (`jaxpr_rules`).

OVL003 (warning) is emitted by the compile pipeline (`jaxfront.api`), not
here: it flags `predict_comm_overlap` running on the flat config guess
rather than a `runtime.calibrate.calibrate_overlap` measurement.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .findings import Finding, make_finding
from .jaxpr_rules import _REDUCING_COLLECTIVES, lint_bucket_plan


def lint_overlap_plan(leaves: Sequence, order: Sequence[int],
                      buckets: Optional[Sequence] = None,
                      node: str = "overlap") -> List[Finding]:
    """Validate an overlapped-flush plan: the emission order must permute
    the leaves (OVL001) and, when given, the bucket plan over the ORDERED
    leaves must tile exactly (COLL003 via `lint_bucket_plan`)."""
    findings: List[Finding] = []
    n = len(leaves)
    try:
        perm = sorted(int(i) for i in order) == list(range(n))
    except (TypeError, ValueError):
        perm = False
    if not perm:
        findings.append(make_finding(
            "OVL001", node,
            f"order {list(order)[:16]}{'...' if len(list(order)) > 16 else ''} "
            f"is not a permutation of range({n})"))
        return findings  # bucket indices are meaningless under a bad order
    if buckets is not None:
        findings.extend(lint_bucket_plan(leaves, buckets))
    return findings


def _ancestor_eqns(jaxpr):
    """eqn index -> set of transitively reachable producer eqn indices."""
    producer = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for ov in eqn.outvars:
            producer[ov] = i
    cache: dict = {}

    def ancestors(i: int) -> set:
        got = cache.get(i)
        if got is not None:
            return got
        out: set = set()
        cache[i] = out  # jaxprs are DAGs; placeholder guards re-entry
        for v in jaxpr.eqns[i].invars:
            if hasattr(v, "val"):  # literal
                continue
            j = producer.get(v)
            if j is not None:
                out.add(j)
                out |= ancestors(j)
        return out

    return ancestors


def lint_overlap_jaxpr(jaxpr, node: str = "overlap") -> List[Finding]:
    """OVL002 over an ISOLATED flush jaxpr: every pair of consecutive
    reducing collectives must be ordered by a dependency path (the barrier
    token chain, or a direct data dependence).  An unordered pair means
    the pin was dropped and the latency-hiding schedule is not the one
    the cost model was calibrated against."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> core jaxpr
    reducing = [i for i, eqn in enumerate(jaxpr.eqns)
                if eqn.primitive.name in _REDUCING_COLLECTIVES]
    if len(reducing) < 2:
        return []
    ancestors = _ancestor_eqns(jaxpr)
    findings: List[Finding] = []
    for a, b in zip(reducing, reducing[1:]):
        if a not in ancestors(b):
            pa = jaxpr.eqns[a].primitive.name
            pb = jaxpr.eqns[b].primitive.name
            findings.append(make_finding(
                "OVL002", node,
                f"consecutive reducing collectives eqn#{a} ({pa}) and "
                f"eqn#{b} ({pb}) have no ordering dependency — the "
                "optimization_barrier token chain is broken"))
    return findings


def lint_overlap_fn(fn, *args, axis_sizes=None, node: str = "overlap",
                    **kwargs) -> List[Finding]:
    """Trace `fn(*args, **kwargs)` (an isolated flush builder) under the
    given axis environment and lint the chain structure (OVL002)."""
    import jax

    axis_env = list((axis_sizes or {}).items())
    closed = jax.make_jaxpr(fn, axis_env=axis_env)(*args, **kwargs)
    return lint_overlap_jaxpr(closed.jaxpr, node=node)
