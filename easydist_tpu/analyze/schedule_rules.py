"""Layer 3b: static verifier over pipeline tick-schedule tables.

Works on the host-side supertick tables that drive the compiled pipeline
scans (`parallel/pipeline.py::_1f1b_schedule_tables` for 1F1B and the
interleaved forward; `gpipe_schedule_tables` below re-derives the plain
GPipe clock `u = s + m` in the same table form).  A schedule bug here does
not crash — the lockstep SPMD scan happily runs masked garbage ticks, so a
unit consuming an activation that has not arrived yet, or a residual ring
slot overwritten before its backward reads it, surfaces as silently wrong
gradients or a hung fill on real TPUs.

  SCHED001  dependency-DAG consistency (deadlock check): the dependency
            graph — fwd(j,m) needs fwd(j-1,m) one tick earlier (ppermute
            latency), bwd(j,m) needs bwd(j+1,m) one tick earlier and its
            own fwd(j,m) — is acyclic by construction, so the schedule is
            deadlock-free iff its tick assignment is a topological order;
            a unit scheduled twice or never scheduled also fires;
  SCHED002  per-stage in-flight activation stash: the max number of
            microbatches a (device, chunk) holds between forward and
            backward must fit both the declared residual ring (an
            overflow overwrites a live vjp residual) and the 1F1B
            theoretical bound min(2*(J-j)-1, M) of stage depth J-j;
  SCHED003  static bubble fraction (warning-level report): idle fwd/bwd
            slots over total slots, against
            `edconfig.analyze_bubble_warn_frac`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .findings import Finding, make_finding

_MAX_PER_CHECK = 8


def gpipe_schedule_tables(n_stages: int, n_microbatches: int) -> Dict:
    """The plain GPipe forward clock `fwd(s, m) at u = s + m` in the same
    table form `_1f1b_schedule_tables` emits, so one verifier covers both
    schedule families (`parallel/pipeline.py::spmd_pipeline` and the
    auto-split `parallel/auto_pipeline.py::pipeline_forward`)."""
    S, M = n_stages, n_microbatches
    U = M + S - 1
    m_f = np.zeros((U, S), np.int32)
    k_f = np.zeros((U, S), np.int32)
    f_ok = np.zeros((U, S), bool)
    for s in range(S):
        for m in range(M):
            m_f[s + m, s], f_ok[s + m, s] = m, True
    zeros = np.zeros((U, S), np.int32)
    return {"m_f": m_f, "k_f": k_f, "f_ok": f_ok,
            "m_b": zeros, "k_b": zeros.copy(),
            "b_ok": np.zeros((U, S), bool),
            "n_superticks": U, "ring": 1}


def _collect_units(tables: Dict, S: int, fwd: bool
                   ) -> Tuple[Dict[Tuple[int, int], int], List]:
    """(global stage j, microbatch m) -> supertick, plus duplicate sites."""
    ok_k, m_k, k_k = (("f_ok", "m_f", "k_f") if fwd
                      else ("b_ok", "m_b", "k_b"))
    ok = np.asarray(tables[ok_k])
    mm = np.asarray(tables[m_k])
    kk = np.asarray(tables[k_k])
    units: Dict[Tuple[int, int], int] = {}
    dups = []
    for u in range(ok.shape[0]):
        for s in range(S):
            if not ok[u, s]:
                continue
            unit = (int(kk[u, s]) * S + s, int(mm[u, s]))
            if unit in units:
                dups.append((unit, units[unit], u))
            else:
                units[unit] = u
    return units, dups


def schedule_stats(tables: Dict, fwd_only: bool = False) -> Dict:
    """Static occupancy numbers for the SCHED003 report / PerfDB export."""
    f_ok = np.asarray(tables["f_ok"])
    useful = int(f_ok.sum())
    total = int(f_ok.size)
    if not fwd_only:
        useful += int(np.asarray(tables["b_ok"]).sum())
        total *= 2
    return {
        "bubble_fraction": 1.0 - useful / max(total, 1),
        "useful_slots": useful,
        "total_slots": total,
        "n_superticks": int(tables["n_superticks"]),
        "ring": int(tables.get("ring", 1)),
    }


def verify_schedule_tables(tables: Dict, n_stages: int, n_virtual: int,
                           n_microbatches: int, fwd_only: bool = False,
                           node: str = "pipeline",
                           bubble_warn_frac: Optional[float] = None
                           ) -> List[Finding]:
    """SCHED001/002/003 over one schedule-table set."""
    findings: List[Finding] = []
    S, V, M = n_stages, max(1, n_virtual), n_microbatches
    J = V * S

    u_fwd, fdups = _collect_units(tables, S, fwd=True)
    u_bwd, bdups = _collect_units(tables, S, fwd=False)

    # ---- SCHED001: scheduled exactly once
    for unit, u0, u1 in (fdups + bdups)[:_MAX_PER_CHECK]:
        j, m = unit
        findings.append(make_finding(
            "SCHED001", f"{node}/stage{j}/mb{m}",
            f"unit scheduled twice (superticks {u0} and {u1}) — one "
            f"execution clobbers the other's slot"))
    missing_f = [(j, m) for j in range(J) for m in range(M)
                 if (j, m) not in u_fwd]
    if missing_f:
        findings.append(make_finding(
            "SCHED001", node,
            f"{len(missing_f)} forward unit(s) never scheduled "
            f"(starvation): {missing_f[:6]}"
            f"{'...' if len(missing_f) > 6 else ''}"))
    if not fwd_only:
        missing_b = [(j, m) for j in range(J) for m in range(M)
                     if (j, m) not in u_bwd]
        if missing_b:
            findings.append(make_finding(
                "SCHED001", node,
                f"{len(missing_b)} backward unit(s) never scheduled: "
                f"{missing_b[:6]}{'...' if len(missing_b) > 6 else ''}"))

    # ---- SCHED001: the tick assignment must topologically order the
    # dependency DAG (activations ride one ppermute tick up the ring,
    # gradients one tick down; the last stage turns around in-tick)
    n_dep = 0
    for (j, m), u in sorted(u_fwd.items()):
        if j == 0 or (j - 1, m) not in u_fwd or n_dep >= _MAX_PER_CHECK:
            continue
        if u <= u_fwd[(j - 1, m)]:
            n_dep += 1
            findings.append(make_finding(
                "SCHED001", f"{node}/stage{j}/mb{m}",
                f"fwd at supertick {u} but its input activation leaves "
                f"stage {j - 1} at supertick {u_fwd[(j - 1, m)]} (+1 tick "
                f"ppermute) — consumes a value that has not arrived"))
    if not fwd_only:
        for (j, m), u in sorted(u_bwd.items()):
            if n_dep >= _MAX_PER_CHECK:
                break
            if j < J - 1 and (j + 1, m) in u_bwd \
                    and u <= u_bwd[(j + 1, m)]:
                n_dep += 1
                findings.append(make_finding(
                    "SCHED001", f"{node}/stage{j}/mb{m}",
                    f"bwd at supertick {u} but its cotangent leaves stage "
                    f"{j + 1} at supertick {u_bwd[(j + 1, m)]} (+1 tick "
                    f"ppermute)"))
            elif (j, m) in u_fwd and u < u_fwd[(j, m)]:
                n_dep += 1
                findings.append(make_finding(
                    "SCHED001", f"{node}/stage{j}/mb{m}",
                    f"bwd at supertick {u} precedes its own fwd at "
                    f"{u_fwd[(j, m)]}"))

    # ---- SCHED002: in-flight stash vs ring and the 1F1B bound (units
    # missing a fwd or bwd tick are skipped here — SCHED001 already fired)
    if not fwd_only:
        ring = int(tables.get("ring", 1))
        over_ring: List[Tuple[int, int, int]] = []   # (live, j, bound)
        over_bound: List[Tuple[int, int, int]] = []
        for k in range(V):
            for s in range(S):
                j = k * S + s
                mbs = [m for m in range(M)
                       if (j, m) in u_fwd and (j, m) in u_bwd]
                if not mbs:
                    continue
                live = max(
                    sum(1 for m2 in mbs
                        if u_fwd[(j, m2)] <= u_bwd[(j, m1)]) - i1
                    for i1, m1 in enumerate(mbs))
                bound = min(2 * (J - j) - 1, M)
                if live > ring:
                    over_ring.append((live, j, ring))
                elif live > bound:
                    over_bound.append((live, j, bound))
        if over_ring:
            live, j, ring = max(over_ring)
            findings.append(make_finding(
                "SCHED002", f"{node}/stage{j}",
                f"{live} microbatches in flight but the residual ring "
                f"holds {ring} slot(s) ({len(over_ring)} stage(s) "
                f"affected) — a live vjp residual is overwritten before "
                f"its backward reads it"))
        elif over_bound:
            live, j, bound = max(over_bound)
            findings.append(make_finding(
                "SCHED002", f"{node}/stage{j}",
                f"{live} microbatches in flight exceeds the 1F1B "
                f"theoretical stash bound min(2*(J-j)-1, M) = {bound} "
                f"({len(over_bound)} stage(s) affected) — the schedule "
                f"keeps gpipe-class activation memory"))

    # ---- SCHED003: bubble-fraction report
    if bubble_warn_frac is None:
        from easydist_tpu import config as edconfig

        bubble_warn_frac = edconfig.analyze_bubble_warn_frac
    stats = schedule_stats(tables, fwd_only=fwd_only)
    if stats["bubble_fraction"] > bubble_warn_frac:
        findings.append(make_finding(
            "SCHED003", node,
            f"static bubble fraction {stats['bubble_fraction']:.2f} "
            f"exceeds {bubble_warn_frac:.2f} "
            f"({stats['useful_slots']}/{stats['total_slots']} useful "
            f"slots over {stats['n_superticks']} superticks) — raise "
            f"n_microbatches or n_virtual"))
    return findings
