"""Native (C++) runtime components, built lazily at first use.

Mirrors the reference's JIT C++ extension loading (torch/meta_allocator.py:
24-69 builds csrc with cpp_extension.load); here a plain g++ -shared build
cached next to the sources and bound with ctypes (no pybind11 in the image).
Falls back to pure-Python implementations when no compiler is present.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "csrc")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build() -> Optional[str]:
    so_path = os.path.join(_DIR, "libed_native.so")
    srcs = [os.path.join(_SRC, f) for f in sorted(os.listdir(_SRC))
            if f.endswith(".cpp")]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(so_path) and os.path.getmtime(so_path) > newest_src:
        return so_path
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", so_path] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so_path
    except Exception as e:
        logger.warning("native build failed (%s); using Python fallbacks", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        so = _build()
        if so is not None:
            lib = ctypes.CDLL(so)
            i64p = ctypes.POINTER(ctypes.c_int64)
            f64p = ctypes.POINTER(ctypes.c_double)
            i32p = ctypes.POINTER(ctypes.c_int32)
            lib.ed_skyline_plan.restype = ctypes.c_int64
            lib.ed_skyline_plan.argtypes = [ctypes.c_int64, i64p, i64p, i64p,
                                            i64p]
            lib.ed_check_plan.restype = ctypes.c_int64
            lib.ed_check_plan.argtypes = [ctypes.c_int64, i64p, i64p, i64p,
                                          i64p, ctypes.c_int64, i64p]
            lib.ed_peak_live.restype = ctypes.c_int64
            lib.ed_peak_live.argtypes = [ctypes.c_int64, i64p, i64p, i64p]
            lib.ed_beam_search.restype = ctypes.c_double
            lib.ed_beam_search.argtypes = [
                ctypes.c_int64, i64p, f64p, i64p, ctypes.c_int64, i64p, i64p,
                f64p, i64p, ctypes.c_int64, i32p]
            _LIB = lib
    return _LIB


def _i64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def _ptr(a, typ):
    return a.ctypes.data_as(ctypes.POINTER(typ))


def available() -> bool:
    return get_lib() is not None


# ----------------------------------------------------------- memory planner

def skyline_plan(starts: Sequence[int], ends: Sequence[int],
                 sizes: Sequence[int]) -> Tuple[np.ndarray, int]:
    """Assign non-overlapping offsets to buffers live over [start, end];
    returns (offsets, peak_bytes)."""
    n = len(starts)
    s, e, z = _i64(starts), _i64(ends), _i64(sizes)
    offsets = np.zeros(n, dtype=np.int64)
    lib = get_lib()
    if lib is not None and n:
        peak = lib.ed_skyline_plan(n, _ptr(s, ctypes.c_int64),
                                   _ptr(e, ctypes.c_int64),
                                   _ptr(z, ctypes.c_int64),
                                   _ptr(offsets, ctypes.c_int64))
        return offsets, int(peak)
    # python fallback: identical greedy best-fit
    order = sorted(range(n), key=lambda i: (-z[i], s[i]))
    placed: List[Tuple[int, int, int, int]] = []
    peak = 0
    for i in order:
        blocked = sorted((off, off + size) for (bs, be, off, size) in placed
                         if bs <= e[i] and s[i] <= be)
        off = 0
        for lo, hi in blocked:
            if off + z[i] <= lo:
                break
            if off < hi:
                off = hi
        placed.append((int(s[i]), int(e[i]), off, int(z[i])))
        offsets[i] = off
        peak = max(peak, off + int(z[i]))
    return offsets, int(peak)


def check_plan(starts, ends, sizes, offsets, max_report: int = 16):
    """Verify lifetime/address disjointness; returns list of violating index
    pairs (empty = valid)."""
    n = len(starts)
    lib = get_lib()
    s, e, z, o = _i64(starts), _i64(ends), _i64(sizes), _i64(offsets)
    if lib is not None:
        report = np.zeros(2 * max_report, dtype=np.int64)
        count = lib.ed_check_plan(n, _ptr(s, ctypes.c_int64),
                                  _ptr(e, ctypes.c_int64),
                                  _ptr(z, ctypes.c_int64),
                                  _ptr(o, ctypes.c_int64),
                                  max_report, _ptr(report, ctypes.c_int64))
        return [(int(report[2 * i]), int(report[2 * i + 1]))
                for i in range(min(count, max_report))]
    out = []
    for i in range(n):
        for j in range(i + 1, n):
            if s[i] <= e[j] and s[j] <= e[i] and \
                    o[i] < o[j] + z[j] and o[j] < o[i] + z[i]:
                out.append((i, j))
    return out


def live_profile(starts, ends, sizes) -> np.ndarray:
    """Sum-of-live-sizes per schedule step (length max(ends)+1) — the full
    curve behind `peak_live`; the analyzer's MEM004 advisory uses its
    argmax to find the peak step a remat candidate must span."""
    n = len(starts)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    s, e, z = _i64(starts), _i64(ends), _i64(sizes)
    max_t = int(e.max())
    delta = np.zeros(max_t + 2, dtype=np.int64)
    np.add.at(delta, s, z)
    np.add.at(delta, e + 1, -z)
    return np.cumsum(delta[:-1])


def peak_live(starts, ends, sizes) -> int:
    """Sum-of-live-sizes peak — the allocator-independent lower bound."""
    n = len(starts)
    if n == 0:
        return 0
    lib = get_lib()
    s, e, z = _i64(starts), _i64(ends), _i64(sizes)
    if lib is not None:
        return int(lib.ed_peak_live(n, _ptr(s, ctypes.c_int64),
                                    _ptr(e, ctypes.c_int64),
                                    _ptr(z, ctypes.c_int64)))
    max_t = int(e.max())
    delta = np.zeros(max_t + 2, dtype=np.int64)
    np.add.at(delta, s, z)
    np.add.at(delta, e + 1, -z)
    return int(np.cumsum(delta).max())


# ------------------------------------------------------------- beam search

def beam_search_native(strat_count, y_cost_list, edges, beam_width: int):
    """Run the C++ beam core.

    strat_count: [n_clusters]; y_cost_list: list of per-cluster cost arrays;
    edges: list of (up, down, cost_matrix[up_s, down_s]).
    Returns (assign array, cost) or None when the native lib is missing.
    """
    lib = get_lib()
    if lib is None:
        return None
    n = len(strat_count)
    sc = _i64(strat_count)
    y_off = np.zeros(n, dtype=np.int64)
    total = 0
    for i, c in enumerate(strat_count):
        y_off[i] = total
        total += int(c)
    y_cost = np.zeros(total, dtype=np.float64)
    for i, costs in enumerate(y_cost_list):
        y_cost[y_off[i]:y_off[i] + len(costs)] = costs

    n_e = len(edges)
    up = _i64([e[0] for e in edges])
    down = _i64([e[1] for e in edges])
    e_off = np.zeros(max(n_e, 1), dtype=np.int64)
    tot = 0
    mats = []
    for i, (u, d, m) in enumerate(edges):
        e_off[i] = tot
        m = np.ascontiguousarray(m, dtype=np.float64)
        mats.append(m.ravel())
        tot += m.size
    edge_cost = np.concatenate(mats) if mats else np.zeros(1)

    assign = np.zeros(n, dtype=np.int32)
    cost = lib.ed_beam_search(
        n, _ptr(sc, ctypes.c_int64), _ptr(y_cost, ctypes.c_double),
        _ptr(y_off, ctypes.c_int64), n_e, _ptr(up, ctypes.c_int64),
        _ptr(down, ctypes.c_int64), _ptr(edge_cost, ctypes.c_double),
        _ptr(e_off, ctypes.c_int64), beam_width,
        _ptr(assign, ctypes.c_int32))
    return assign, float(cost)
