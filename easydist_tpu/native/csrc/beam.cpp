// Beam-search strategy solver core.
//
// The compile-time hot loop of strategy selection (the Python fallback in
// autoflow/solver.py beam_search; reference formulation autoflow/
// solver.py:814-890).  For large graphs (thousands of clusters) the Python
// loop dominates compile time; this C++ core runs the identical algorithm
// over flattened cost matrices.
//
// Inputs (flattened, C ABI):
//   n_clusters, strat_count[c]
//   y_cost: per-cluster linear costs, laid out cluster-major
//           (offset y_off[c], length strat_count[c])
//   n_edges, edge_up[e], edge_down[e]: cluster ids
//   edge_cost: matrices laid out edge-major (offset e_off[e],
//              row-major [strat_count[up] x strat_count[down]])
//   beam_width
// Output: chosen strategy index per cluster; returns best cost.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {

struct Candidate {
  double cost;
  std::vector<int32_t> assign;  // strategy per cluster processed so far
};

}  // namespace

extern "C" {

double ed_beam_search(int64_t n_clusters, const int64_t* strat_count,
                      const double* y_cost, const int64_t* y_off,
                      int64_t n_edges, const int64_t* edge_up,
                      const int64_t* edge_down, const double* edge_cost,
                      const int64_t* e_off, int64_t beam_width,
                      int32_t* assign_out) {
  // index edges by endpoint for incremental cost evaluation
  std::vector<std::vector<int64_t>> in_edges(n_clusters), out_edges(n_clusters);
  for (int64_t e = 0; e < n_edges; ++e) {
    in_edges[edge_down[e]].push_back(e);
    out_edges[edge_up[e]].push_back(e);
  }

  std::vector<Candidate> beam(1);
  beam[0].cost = 0.0;

  for (int64_t c = 0; c < n_clusters; ++c) {
    std::vector<Candidate> grown;
    grown.reserve(beam.size() * strat_count[c]);
    for (const Candidate& cand : beam) {
      for (int32_t s = 0; s < strat_count[c]; ++s) {
        double delta = y_cost[y_off[c] + s];
        // edge charged when its SECOND endpoint is assigned
        for (int64_t e : in_edges[c]) {
          const int64_t up = edge_up[e];
          if (up < c) {
            const int64_t n_down = strat_count[c];
            delta += edge_cost[e_off[e] + cand.assign[up] * n_down + s];
          }
        }
        for (int64_t e : out_edges[c]) {
          const int64_t down = edge_down[e];
          if (down < c) {
            const int64_t n_down = strat_count[down];
            delta += edge_cost[e_off[e] + s * n_down + cand.assign[down]];
          }
        }
        grown.push_back(cand);
        grown.back().cost += delta;
        grown.back().assign.push_back(s);
      }
    }
    const size_t keep = std::min<size_t>(grown.size(),
                                         static_cast<size_t>(beam_width));
    std::partial_sort(grown.begin(), grown.begin() + keep, grown.end(),
                      [](const Candidate& a, const Candidate& b) {
                        return a.cost < b.cost;
                      });
    grown.resize(keep);
    beam.swap(grown);
  }

  const Candidate& best = beam.front();
  std::memcpy(assign_out, best.assign.data(),
              sizeof(int32_t) * static_cast<size_t>(n_clusters));
  return best.cost;
}

}  // extern "C"
