// Tokenized-binary data loader with background prefetch.
//
// The host-side input pipeline (the IO role the reference fills with its
// C++ tensorfield memory pool + python loaders): memory-maps a flat token
// file, samples random windows, and fills a ring of ready batches from a
// producer thread so the accelerator never waits on the host.  C ABI for
// ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <random>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Loader {
  // mapping
  void* map = nullptr;
  size_t file_bytes = 0;
  int fd = -1;
  int64_t n_tokens = 0;
  int token_bytes = 2;  // uint16 (GPT-2 style) or 4 (uint32)

  // batch geometry
  int64_t batch = 0, window = 0;  // window = seq + 1 (inputs+targets)

  // prefetch ring
  std::vector<std::vector<int32_t>> ring;
  size_t head = 0, tail = 0, count = 0;
  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::thread worker;
  std::atomic<bool> stop{false};
  std::mt19937_64 rng;

  void produce_loop() {
    std::uniform_int_distribution<int64_t> dist(
        0, n_tokens - window - 1);
    while (!stop.load()) {
      // sample a batch outside the lock
      std::vector<int32_t> buf(static_cast<size_t>(batch) * window);
      for (int64_t b = 0; b < batch; ++b) {
        const int64_t start = dist(rng);
        for (int64_t t = 0; t < window; ++t) {
          const int64_t idx = start + t;
          int32_t tok;
          if (token_bytes == 2) {
            tok = reinterpret_cast<const uint16_t*>(map)[idx];
          } else {
            tok = reinterpret_cast<const int32_t*>(map)[idx];
          }
          buf[static_cast<size_t>(b) * window + t] = tok;
        }
      }
      std::unique_lock<std::mutex> lock(mu);
      cv_produce.wait(lock, [&] { return stop.load() || count < ring.size(); });
      if (stop.load()) return;
      ring[head].swap(buf);
      head = (head + 1) % ring.size();
      ++count;
      cv_consume.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* ed_loader_open(const char* path, int token_bytes, int64_t batch,
                     int64_t window, int64_t n_prefetch, uint64_t seed) {
  auto* L = new Loader();
  L->fd = ::open(path, O_RDONLY);
  if (L->fd < 0) {
    delete L;
    return nullptr;
  }
  struct stat st;
  if (fstat(L->fd, &st) != 0 || st.st_size < token_bytes * (window + 1)) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  L->file_bytes = static_cast<size_t>(st.st_size);
  L->map = mmap(nullptr, L->file_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (L->map == MAP_FAILED) {
    ::close(L->fd);
    delete L;
    return nullptr;
  }
  L->token_bytes = token_bytes;
  L->n_tokens = static_cast<int64_t>(L->file_bytes / token_bytes);
  L->batch = batch;
  L->window = window;
  L->ring.resize(static_cast<size_t>(n_prefetch));
  L->rng.seed(seed);
  L->worker = std::thread([L] { L->produce_loop(); });
  return L;
}

// Copies one ready batch ([batch, window] int32) into out; blocks until
// available.  Returns 0 on success.
int ed_loader_next(void* handle, int32_t* out) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lock(L->mu);
  L->cv_consume.wait(lock, [&] { return L->count > 0; });
  std::memcpy(out, L->ring[L->tail].data(),
              sizeof(int32_t) * static_cast<size_t>(L->batch) * L->window);
  L->tail = (L->tail + 1) % L->ring.size();
  --L->count;
  L->cv_produce.notify_one();
  return 0;
}

int64_t ed_loader_num_tokens(void* handle) {
  return static_cast<Loader*>(handle)->n_tokens;
}

void ed_loader_close(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  L->cv_produce.notify_all();
  L->cv_consume.notify_all();
  if (L->worker.joinable()) L->worker.join();
  if (L->map != nullptr && L->map != MAP_FAILED) munmap(L->map, L->file_bytes);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
