// Skyline memory planner + lifetime overlap checker.
//
// TPU-native counterpart of the reference's C++ memory machinery: the
// profiling allocator's planned-address replay (easydist/torch/profiler/
// csrc/profiling_allocator.cpp) and the EfficientMemoryScheduler's skyline
// address assignment (torch/schedule/efficient_memory_scheduler.py:32-120).
// On TPU, XLA owns the real allocator, so the planner's role is *analysis*:
// given buffer lifetimes+sizes (from liveness or a compiled module), compute
// a fragmentation-aware peak and offsets, and verify lifetime disjointness
// (the op_mem_checker analog, compile_auto.py:269-351).
//
// C ABI, bound from Python with ctypes.

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

struct Buf {
  int64_t start, end, size;  // live over [start, end] inclusive
  int64_t idx;
};

}  // namespace

extern "C" {

// Greedy best-fit skyline: buffers sorted by size descending are placed at
// the lowest offset where they do not overlap (in time AND address) any
// already-placed buffer.  Writes per-buffer offsets; returns peak bytes.
int64_t ed_skyline_plan(int64_t n, const int64_t* starts, const int64_t* ends,
                        const int64_t* sizes, int64_t* offsets_out) {
  std::vector<Buf> bufs(n);
  for (int64_t i = 0; i < n; ++i) bufs[i] = {starts[i], ends[i], sizes[i], i};
  std::stable_sort(bufs.begin(), bufs.end(), [](const Buf& a, const Buf& b) {
    if (a.size != b.size) return a.size > b.size;
    return a.start < b.start;
  });

  struct Placed {
    int64_t start, end, off, size;
  };
  std::vector<Placed> placed;
  placed.reserve(n);
  int64_t peak = 0;

  std::vector<std::pair<int64_t, int64_t>> blocked;  // addr ranges in conflict
  for (const Buf& b : bufs) {
    blocked.clear();
    for (const Placed& p : placed) {
      if (p.start <= b.end && b.start <= p.end) {
        blocked.emplace_back(p.off, p.off + p.size);
      }
    }
    std::sort(blocked.begin(), blocked.end());
    int64_t off = 0;
    for (const auto& [lo, hi] : blocked) {
      if (off + b.size <= lo) break;  // fits in the gap before this range
      if (off < hi) off = hi;
    }
    placed.push_back({b.start, b.end, off, b.size});
    offsets_out[b.idx] = off;
    peak = std::max(peak, off + b.size);
  }
  return peak;
}

// Lifetime-overlap verification: returns the number of pairs of buffers
// whose address ranges overlap while both are live (0 = plan is valid).
// First `max_report` offending pairs are written to report_out (i, j).
int64_t ed_check_plan(int64_t n, const int64_t* starts, const int64_t* ends,
                      const int64_t* sizes, const int64_t* offsets,
                      int64_t max_report, int64_t* report_out) {
  int64_t violations = 0;
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t j = i + 1; j < n; ++j) {
      const bool time_overlap = starts[i] <= ends[j] && starts[j] <= ends[i];
      if (!time_overlap) continue;
      const bool addr_overlap = offsets[i] < offsets[j] + sizes[j] &&
                                offsets[j] < offsets[i] + sizes[i];
      if (addr_overlap) {
        if (violations < max_report) {
          report_out[2 * violations] = i;
          report_out[2 * violations + 1] = j;
        }
        ++violations;
      }
    }
  }
  return violations;
}

// Peak live bytes without packing (sum of live sizes per tick) — the
// lower bound any allocator can reach.
int64_t ed_peak_live(int64_t n, const int64_t* starts, const int64_t* ends,
                     const int64_t* sizes) {
  if (n == 0) return 0;
  int64_t max_t = 0;
  for (int64_t i = 0; i < n; ++i) max_t = std::max(max_t, ends[i]);
  std::vector<int64_t> delta(static_cast<size_t>(max_t) + 2, 0);
  for (int64_t i = 0; i < n; ++i) {
    delta[starts[i]] += sizes[i];
    delta[ends[i] + 1] -= sizes[i];
  }
  int64_t cur = 0, peak = 0;
  for (int64_t t = 0; t <= max_t; ++t) {
    cur += delta[t];
    peak = std::max(peak, cur);
  }
  return peak;
}

}  // extern "C"
