"""easydist_tpu: a TPU-native automatic-parallelization framework.

One-decorator parallelization of unmodified JAX train/inference step functions:
trace to jaxpr, discover per-op SPMD sharding rules by executing each op sharded
and checking recombination (ShardCombine), solve a global ILP for the
minimum-communication strategy over an ICI/DCN device mesh, and emit the original
function with `jax.lax.with_sharding_constraint` so XLA's GSPMD partitioner
inserts the collectives.  No CUDA/NCCL anywhere.

Capability parity target: alibaba/easydist (see /root/reference) — user API
`easydist_setup` + `easydist_compile` (reference easydist/__init__.py:21,
easydist/jax/api.py:307), rebuilt TPU-first.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401


def easydist_setup(backend: str = "jax", device: str = "tpu", allow_tf32: bool = True):
    """Initialize the framework (reference: easydist/__init__.py:21-36).

    On TPU there is no NCCL/process-group bring-up: multi-host initialization is
    `jax.distributed.initialize()` over DCN, and single-host needs nothing.
    """
    import logging

    logging.basicConfig(level=config.log_level)
    from .platform import init_backend

    init_backend(backend)
    if backend == "jax" and config.multihost:
        import jax

        jax.distributed.initialize()


def easydist_compile(func=None, **kwargs):
    """Decorator entrypoint; dispatches to the JAX frontend.

    Mirrors reference easydist/jax/api.py:307-323 (and torch/api.py:227 for the
    torch frontend, which lowers to the same IR).
    """
    from .jaxfront.api import easydist_compile as _jax_compile

    return _jax_compile(func, **kwargs)
