"""Prefix-reuse KV cache: a reference-counted token trie over committed
KV chunks.

Serving traffic shares prompt prefixes massively (system prompts, few-shot
preambles), and PR 9's prefill recomputed every admitted prompt's KV from
position 0.  This module indexes **committed KV chunks** — the K/V a
finished prefill produced for one aligned `prefill_chunk`-token window —
by their token ids, so the next prompt sharing a prefix restores the
longest cached run of whole chunks with `dynamic_update_slice` and resumes
prefill at `prefix_len` instead of 0.

Design points:

  * **trie over chunks, not tokens** — each node is one aligned chunk
    (positions [depth*C, (depth+1)*C)); children are keyed by the chunk's
    token-id tuple, so lookup is O(prompt/C) dict hops and two prompts
    share a node iff they agree on EVERY token up to that chunk boundary.
    Chunk alignment from position 0 is what makes reuse sound: a cached
    chunk's K/V depends only on the tokens at and before it (causal
    attention, absolute positions), never on what followed.
  * **refcounts pin live prefixes** — admission pins every restored node
    for the slot's lifetime (eviction of a chunk another request is
    actively built on would free device buffers still referenced);
    retirement unpins.
  * **LRU eviction under a byte budget** — `prefix_cache_bytes` bounds the
    sum of committed chunk bytes; eviction walks leaf-first (a node's
    children always depend on it) among unpinned nodes, oldest
    `last_used` first.
  * **bitwise contract** — restore copies the exact arrays a previous
    prefill committed, and the chunked prefill attends the full bucket
    window either way, so prefix-cache-on and -off produce bitwise
    identical logits.  `check_invariants` audits the refcount/byte
    bookkeeping; analyze rule SERVE002 wraps it into findings.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixCache", "chunk_key"]


def chunk_key(tokens: Sequence[int]) -> Tuple[int, ...]:
    """Hashable identity of one chunk: the token-id tuple itself (exact —
    dict hashing gives the 'chunk hash' without collision risk)."""
    return tuple(int(t) for t in tokens)


class _Node:
    """One committed chunk: `kv` is {"k", "v"} of shape
    [layers, (kv_)heads, chunk, head_dim] (device arrays)."""

    __slots__ = ("key", "parent", "children", "kv", "nbytes", "refcount",
                 "last_used", "depth")

    def __init__(self, key, parent, kv, nbytes, depth, tick):
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.kv = kv
        self.nbytes = nbytes
        self.refcount = 0
        self.last_used = tick
        self.depth = depth


class PrefixCache:
    """Token-trie index over committed KV chunks of `chunk` tokens each,
    LRU-evicted under `byte_budget` (0 disables committing entirely)."""

    def __init__(self, chunk: int, byte_budget: int, on_evict=None):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        if byte_budget < 0:
            raise ValueError(f"byte_budget must be >= 0, got {byte_budget}")
        self.chunk = chunk
        self.byte_budget = byte_budget
        # called with each evicted node AFTER unlinking — the paged KV
        # session releases the node's arena page refcount here, so trie
        # eviction is what returns shared pages to the pool
        self.on_evict = on_evict
        self._root = _Node(key=None, parent=None, kv=None, nbytes=0,
                           depth=-1, tick=0)
        self._tick = 0
        self.bytes_used = 0
        self.n_nodes = 0
        self.hits = 0            # chunks served from the trie
        self.misses = 0          # lookups that stopped short of max_chunks
        self.evictions = 0

    # -------------------------------------------------------------- lookup
    def match(self, prompt: Sequence[int],
              max_tokens: Optional[int] = None) -> Tuple[int, List[_Node]]:
        """Longest cached whole-chunk prefix of `prompt`, capped at
        `max_tokens` (callers cap below len(prompt) so at least one real
        token always runs through prefill to produce logits).  Returns
        (prefix_len, nodes) with prefix_len == len(nodes) * chunk; bumps
        LRU ticks on every matched node."""
        limit = len(prompt) if max_tokens is None else min(
            len(prompt), max_tokens)
        max_chunks = limit // self.chunk
        node = self._root
        nodes: List[_Node] = []
        self._tick += 1
        for j in range(max_chunks):
            key = chunk_key(prompt[j * self.chunk:(j + 1) * self.chunk])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick
            nodes.append(child)
            node = child
        self.hits += len(nodes)
        if len(nodes) < max_chunks:
            self.misses += max_chunks - len(nodes)
        return len(nodes) * self.chunk, nodes

    def peek(self, prompt: Sequence[int],
             max_tokens: Optional[int] = None) -> int:
        """Length (tokens) of the longest cached whole-chunk prefix of
        `prompt` WITHOUT touching LRU ticks or hit/miss counters — the
        fleet router probes every replica's trie per request, and a probe
        that mutated recency would let routing decisions evict pages the
        chosen replica is about to restore."""
        limit = len(prompt) if max_tokens is None else min(
            len(prompt), max_tokens)
        node = self._root
        matched = 0
        for j in range(limit // self.chunk):
            child = node.children.get(
                chunk_key(prompt[j * self.chunk:(j + 1) * self.chunk]))
            if child is None:
                break
            matched += 1
            node = child
        return matched * self.chunk

    def lookup_node(self, nodes: List[_Node],
                    chunk_tokens: Sequence[int]) -> Optional[_Node]:
        """Child of the path `nodes` (empty = root) for `chunk_tokens`,
        or None — lets the scheduler skip device extraction for chunks
        that are already committed."""
        parent = nodes[-1] if nodes else self._root
        return parent.children.get(chunk_key(chunk_tokens))

    # -------------------------------------------------------------- commit
    def commit(self, nodes: List[_Node], chunk_tokens: Sequence[int],
               kv, nbytes: Optional[int] = None) -> Optional[_Node]:
        """Commit one chunk's KV under the path `nodes` (which must be the
        contiguous prefix path from the root).  Returns the (existing or
        new) node, or None when the budget is 0 or the chunk is partial.
        Evicts LRU unpinned leaves to stay under the byte budget; a chunk
        larger than the whole budget is not committed.  `nbytes` overrides
        the size computed from `kv`'s array leaves — the paged KV session
        commits page REFERENCES ({"page": id}), whose cost is the arena
        page's bytes, not the reference's."""
        if self.byte_budget == 0 or len(chunk_tokens) != self.chunk:
            return None
        parent = nodes[-1] if nodes else self._root
        key = chunk_key(chunk_tokens)
        existing = parent.children.get(key)
        if existing is not None:
            existing.last_used = self._tick
            return existing
        if nbytes is None:
            nbytes = sum(int(leaf.size) * leaf.dtype.itemsize
                         for leaf in kv.values())
        if nbytes > self.byte_budget:
            return None
        # the path being extended must survive this commit's eviction:
        # the tail is an unpinned leaf until the caller pins the full
        # path, and evicting it here would attach the new node to a
        # detached parent (unreachable subtree + byte-counter drift)
        self.pin(nodes)
        try:
            self._evict_to(self.byte_budget - nbytes)
        finally:
            self.unpin(nodes)
        if self.bytes_used + nbytes > self.byte_budget:
            return None  # everything evictable is pinned
        node = _Node(key=key, parent=parent, kv=kv, nbytes=nbytes,
                     depth=parent.depth + 1, tick=self._tick)
        parent.children[key] = node
        self.bytes_used += nbytes
        self.n_nodes += 1
        return node

    def _evict_to(self, budget: int) -> None:
        while self.bytes_used > budget:
            victim = None
            for node in self._walk():
                if node.children or node.refcount > 0:
                    continue
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                return
            del victim.parent.children[victim.key]
            self.bytes_used -= victim.nbytes
            self.n_nodes -= 1
            self.evictions += 1
            if self.on_evict is not None:
                self.on_evict(victim)

    def evict_lru(self) -> bool:
        """Evict the least-recently-used unpinned leaf on demand — the
        paged KV session calls this when admission needs arena room, to
        hand trie-held pages back to the pool (via `on_evict`) ahead of
        the byte budget forcing it.  Returns True when something was
        evicted."""
        before = self.n_nodes
        self._evict_to(self.bytes_used - 1)
        return self.n_nodes < before

    def evict_node(self, node: _Node) -> bool:
        """Evict one SPECIFIC unpinned childless node.  `evict_lru`'s
        byte-driven walk cannot express "only victims holding a device
        page", which the host tier's eviction fallback needs (evicting a
        demoted node frees no arena page), so the tier picks its victim
        via `lru_node` and unlinks it here."""
        if node.children or node.refcount > 0:
            return False
        del node.parent.children[node.key]
        self.bytes_used -= node.nbytes
        self.n_nodes -= 1
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict(node)
        return True

    def _walk(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    # ------------------------------------------------------- export/import
    def export_path(self, prompt: Sequence[int],
                    max_tokens: Optional[int] = None) -> List[Tuple[
                        Tuple[int, ...], Dict[str, object]]]:
        """Hand the longest cached whole-chunk prefix of `prompt` out as
        [(chunk_tokens, kv)] pairs for transfer to another trie (drain
        page migration, disaggregated-prefill handoff).  Does not evict or
        unpin anything — the pages stay committed here; the caller decides
        the source trie's fate."""
        limit = len(prompt) if max_tokens is None else min(
            len(prompt), max_tokens)
        node = self._root
        out: List[Tuple[Tuple[int, ...], Dict[str, object]]] = []
        for j in range(limit // self.chunk):
            key = chunk_key(prompt[j * self.chunk:(j + 1) * self.chunk])
            child = node.children.get(key)
            if child is None:
                break
            out.append((key, child.kv))
            node = child
        return out

    def hot_paths(self, min_refcount: int = 0) -> List[List[Tuple[
            Tuple[int, ...], Dict[str, object]]]]:
        """Root-to-leaf chunk paths worth migrating on drain: every path
        ending at a leaf whose refcount > `min_refcount`, plus (with the
        default 0) all leaves — ordered hottest-first by the leaf's LRU
        tick so a byte-budget-limited importer keeps the most recent."""
        paths = []
        for node in self._walk():
            if node.children or node.refcount < min_refcount:
                continue
            path = []
            cur = node
            while cur is not self._root:
                path.append((cur.key, cur.kv))
                cur = cur.parent
            paths.append((node.last_used, list(reversed(path))))
        paths.sort(key=lambda t: -t[0])
        return [p for _, p in paths]

    def import_path(self, path: Sequence[Tuple[Tuple[int, ...],
                                               Dict[str, object]]]) -> int:
        """Commit a chunk path exported from another trie, root-first.
        First-commit-wins exactly like `commit` (an existing node keeps
        its kv — both sides computed bitwise-identical pages, so either
        copy serves).  Returns the number of chunks now present along the
        path (existing + newly committed); stops early when the byte
        budget refuses a chunk (children without their parent would be
        unreachable)."""
        nodes: List[_Node] = []
        for key, kv in path:
            node = self.commit(nodes, key, kv)
            if node is None:
                break
            nodes.append(node)
        return len(nodes)

    # ----------------------------------------------------------- refcounts
    def pin(self, nodes: Sequence[_Node]) -> None:
        """Hold `nodes` against eviction for a slot's lifetime."""
        for node in nodes:
            node.refcount += 1

    def unpin(self, nodes: Sequence[_Node]) -> None:
        for node in nodes:
            node.refcount -= 1

    # ---------------------------------------------------------- tier hooks
    def lru_node(self, predicate=None) -> Optional[_Node]:
        """Least-recently-used unpinned node matching `predicate`,
        INTERIOR nodes included — the host tier's demotion victim
        selector.  Unlike eviction (which must unlink childless nodes to
        keep the trie connected), demotion swaps a node's kv in place and
        leaves it in the trie, so any unpinned node still holding a
        device page is fair game even when its descendants do too."""
        victim = None
        for node in self._walk():
            if node.refcount > 0:
                continue
            if predicate is not None and not predicate(node):
                continue
            if victim is None or node.last_used < victim.last_used:
                victim = node
        return victim

    def reaccount(self, node: _Node, nbytes: int, kv=None) -> None:
        """Atomically swap a node's kv value and re-charge its byte cost
        (demotion: `{"page": id}` -> `{"host": key}` at 0 bytes;
        promotion: back to the arena page's bytes).  Keeping `node.nbytes`
        and `self.bytes_used` in one motion is what keeps
        `check_invariants`' byte audit sound across tier moves."""
        self.bytes_used += nbytes - node.nbytes
        node.nbytes = nbytes
        if kv is not None:
            node.kv = kv

    # ----------------------------------------------------------- reporting
    def stats(self) -> Dict[str, int]:
        total = self.hits + self.misses
        return {"nodes": self.n_nodes, "bytes_used": self.bytes_used,
                "byte_budget": self.byte_budget, "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0}

    def check_invariants(self) -> List[str]:
        """Refcount/byte-accounting audit (analyze SERVE002 wraps these
        into findings): byte counter vs actual node sum, non-negative
        refcounts, parent/child link consistency, node count."""
        problems: List[str] = []
        seen_bytes = 0
        seen_nodes = 0
        for node in self._walk():
            seen_nodes += 1
            seen_bytes += node.nbytes
            if node.refcount < 0:
                problems.append(
                    f"node depth={node.depth} has negative refcount "
                    f"{node.refcount} (unbalanced pin/unpin)")
            if node.parent.children.get(node.key) is not node:
                problems.append(
                    f"node depth={node.depth} not linked from its parent "
                    f"(trie structure corrupted)")
        if seen_bytes != self.bytes_used:
            problems.append(
                f"byte accounting drift: counter {self.bytes_used} != "
                f"sum of node bytes {seen_bytes}")
        if seen_nodes != self.n_nodes:
            problems.append(
                f"node count drift: counter {self.n_nodes} != walked "
                f"{seen_nodes}")
        return problems
