"""`ServeEngine`: a shape-bucketed continuous-batching endpoint over an
easydist-compiled inference function.

Shape bucketing is the core economics: XLA specializes one executable per
input shape, so unconstrained request shapes would compile per-request.
The engine pads every packed batch up to configured `batch_buckets` x
`seq_buckets`, giving a closed, warmable set of executables — each bucket
compiles exactly once (the `jaxfront` signature cache guarantees it) and
every subsequent request is a cache hit.

Robustness is layered in from `admission.py` and `resilience/`: bounded-
queue backpressure at submit, per-request deadlines enforced by the
batcher, transient-failure retry with jittered deadline-respecting backoff
around execution, and graceful degradation on three axes —

  * a batch bucket whose compile exhausts device memory is disabled and
    its requests re-packed into smaller enabled buckets;
  * a per-batch execute watchdog (`exec_timeout_ms`) abandons a wedged
    dispatch and fails the batch with `ExecTimeoutError` instead of
    pinning every downstream request behind it;
  * a circuit breaker (`breaker_failure_threshold` > 0) sheds load at
    submit with `CircuitOpenError` once the executor fails persistently
    (or p99 execute latency brows out past `breaker_p99_threshold_ms`),
    probing recovery after `breaker_cooldown_ms`.

`health()` summarizes all of it for a readiness endpoint.  The paths are
exercised deterministically by the `serve.exec_timeout` and
`serve.oom_bucket` fault points (resilience/faultinject.py).
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from easydist_tpu.resilience import faultinject
from easydist_tpu.resilience.breaker import CircuitBreaker

from .admission import (AdmissionController, CircuitOpenError,
                        ExecTimeoutError, QueueFullError,
                        RequestTooLargeError, ServeError, is_oom_error,
                        is_transient_error, retry_transient)
from .batcher import (MicroBatcher, Request, RequestQueue, pack_requests,
                      scatter_results, select_bucket)
from .metrics import ServeMetrics

logger = logging.getLogger(__name__)


def _default_speculate_k() -> int:
    # read at ServeConfig construction (not import) so monkeypatching
    # edconfig.speculate_k takes effect without rebuilding the dataclass
    from easydist_tpu import config as edconfig

    return int(getattr(edconfig, "speculate_k", 0))


def _default_speculate_drafter() -> str:
    from easydist_tpu import config as edconfig

    return str(getattr(edconfig, "speculate_drafter", "ngram"))


@dataclass(frozen=True)
class ServeConfig:
    """Bucketing + batching + admission policy for one engine.

    batch_buckets: allowed padded batch sizes, ascending not required.
    seq_buckets: allowed padded leading-dim lengths for array args (None =
        requests must agree exactly on shapes; only the batch dim pads).
    max_wait_ms: how long the batcher holds the first request of a batch
        open for stragglers (latency floor vs occupancy knob).
    max_queue: bounded queue depth; submits beyond it raise QueueFullError.
    default_deadline_ms: deadline applied when submit() passes none.
    max_retries / retry_backoff_ms / retry_jitter: transient-failure policy
        per batch (jitter stretches each backoff by up to that fraction).
    pad_value: fill for seq padding (e.g. the pad token id).
    unpad_outputs: slice outputs back to each request's original length.
    exec_timeout_ms: per-batch execute watchdog; None disables.
    breaker_failure_threshold: consecutive executor failures before the
        circuit opens; 0 disables the breaker entirely.
    breaker_cooldown_ms: how long the open circuit sheds before probing.
    breaker_p99_threshold_ms / breaker_min_samples: optional brownout trip
        on observed p99 execute latency.
    decode_buckets: allowed KV-cache max-lengths for token-level decode
        (serve/generation.py); each bucket owns one slot pool and exactly
        one compiled decode step.
    kv_cache_dtype: cache storage dtype ("auto" = the model's dtype);
        shape/dtype-visible in every decode signature.
    max_decode_slots: slots per decode bucket — the fixed decode batch
        width (idle slots show up as occupancy, never as a new signature).
    prefill_chunk: token window of one chunked-prefill pass — prompts run
        in fixed [prefill_batch, prefill_chunk] chunk calls, so ONE
        compiled prefill signature per bucket serves every prompt length;
        also the prefix-cache chunk granularity (reuse is whole chunks).
    prefill_batch: staging rows — how many pending prompts pack into a
        single chunked-prefill call.
    prefill_chunks_per_step: chunk calls interleaved per `step()` before
        the decode rounds run — bounds decode p99 under prefill pressure.
    enable_prefix_cache: commit/restore prefix KV chunks via the token
        trie (serve/prefix_cache.py); off = every prompt recomputes from
        position 0 (bitwise-identical outputs either way).
    prefix_cache_bytes: LRU byte budget per decode bucket's trie; 0
        disables committing.
    kv_layout: decode KV storage — "bucketed" (one padded slot pool per
        decode bucket, the PR 8-11 layout) or "paged" (ONE page-granular
        pool over a preallocated arena: arbitrary lengths in one compiled
        decode step, no bucket padding, zero-copy prefix restore; needs
        the paged model callables `for_gpt`/`for_llama` wire).
    kv_page_tokens: tokens per KV page in the paged layout; 0 = the
        effective prefill chunk (pages ARE the prefix-trie chunks, which
        is what makes restore a pure table mapping).
    kv_arena_pages: arena size in pages; 0 = auto
        (max_decode_slots * pages-per-sequence + one sequence's worth of
        headroom for trie-held pages).
    speculate_k: draft tokens proposed per speculative-decoding verify
        round (serve/speculate.py); 0 disables speculation.  The verify
        program scores [slots, k+1] positions in one fixed-shape call —
        k is a shape, so changing it means one new compiled signature.
        Output is bitwise-identical to speculate_k=0 (greedy parity).
    speculate_drafter: "ngram" (zero-cost self-speculative prompt
        lookup) or "draft_model" (a second small model's cached greedy
        decode; the session must be given a drafter or draft_model).
    kv_quant_dtype: "none" (exact storage — the bitwise path) or "int8"
        (paged arena pages stored block-scaled int8 with a parallel f32
        scale arena; ~4x sequences per HBM byte, greedy output gated by
        the bounded-drift A/B harness rather than bitwise).  Paged layout
        only, and mutually exclusive with a non-auto kv_cache_dtype.
    kv_quant_block: head-dim elements per quantization block (one f32
        scale each); 0 = one block per K/V row (head_dim).  Must divide
        head_dim.
    kv_host_tier_bytes: host-RAM byte budget for demoting cold unpinned
        prefix-trie pages out of the HBM arena (kv/tier.py; chunked
        fetches, sha256 manifests, promote-on-hit); 0 disables the tier.
        Paged layout with the prefix cache enabled only.
    """
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    seq_buckets: Optional[Tuple[int, ...]] = None
    max_wait_ms: float = 5.0
    max_queue: int = 256
    default_deadline_ms: Optional[float] = None
    max_retries: int = 2
    retry_backoff_ms: float = 10.0
    retry_jitter: float = 0.25
    pad_value: object = 0
    unpad_outputs: bool = True
    exec_timeout_ms: Optional[float] = None
    breaker_failure_threshold: int = 0
    breaker_cooldown_ms: float = 1000.0
    breaker_p99_threshold_ms: Optional[float] = None
    breaker_min_samples: int = 20
    decode_buckets: Tuple[int, ...] = (1024,)
    kv_cache_dtype: str = "auto"
    max_decode_slots: int = 8
    prefill_chunk: int = 64
    prefill_batch: int = 4
    prefill_chunks_per_step: int = 4
    enable_prefix_cache: bool = True
    prefix_cache_bytes: int = 64 * 2**20
    kv_layout: str = "bucketed"
    kv_page_tokens: int = 0
    kv_arena_pages: int = 0
    speculate_k: int = field(
        default_factory=lambda: _default_speculate_k())
    speculate_drafter: str = field(
        default_factory=lambda: _default_speculate_drafter())
    kv_quant_dtype: str = "none"
    kv_quant_block: int = 0
    kv_host_tier_bytes: int = 0

    def __post_init__(self):
        if not self.batch_buckets:
            raise ValueError("batch_buckets must be non-empty")
        if any(b < 1 for b in self.batch_buckets):
            raise ValueError(f"batch buckets must be >= 1: "
                             f"{self.batch_buckets}")
        if self.seq_buckets is not None and not self.seq_buckets:
            raise ValueError("seq_buckets must be None or non-empty")
        if not 0.0 <= self.retry_jitter <= 1.0:
            raise ValueError(
                f"retry_jitter must be in [0, 1], got {self.retry_jitter}")
        if self.exec_timeout_ms is not None and self.exec_timeout_ms <= 0:
            raise ValueError(f"exec_timeout_ms must be > 0 or None, "
                             f"got {self.exec_timeout_ms}")
        if self.breaker_failure_threshold < 0:
            raise ValueError(
                f"breaker_failure_threshold must be >= 0 (0 disables), "
                f"got {self.breaker_failure_threshold}")
        if self.breaker_cooldown_ms <= 0:
            raise ValueError(f"breaker_cooldown_ms must be > 0, "
                             f"got {self.breaker_cooldown_ms}")
        if not self.decode_buckets or any(b < 1 for b in self.decode_buckets):
            raise ValueError(f"decode_buckets must be non-empty with every "
                             f"bucket >= 1: {self.decode_buckets}")
        if self.kv_cache_dtype != "auto":
            try:
                np.dtype(self.kv_cache_dtype)
            except TypeError:
                raise ValueError(
                    f"kv_cache_dtype must be 'auto' or a numpy-parseable "
                    f"dtype name, got {self.kv_cache_dtype!r}") from None
        if self.max_decode_slots < 1:
            raise ValueError(f"max_decode_slots must be >= 1, "
                             f"got {self.max_decode_slots}")
        if self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, "
                             f"got {self.prefill_chunk}")
        for b in self.decode_buckets:
            # the effective chunk (min(prefill_chunk, bucket)) must tile
            # the bucket exactly: a chunk write that would spill past the
            # bucket gets its start CLAMPED by dynamic_update_slice,
            # silently corrupting earlier cache rows
            eff = min(self.prefill_chunk, b)
            if b % eff != 0:
                raise ValueError(
                    f"decode bucket {b} is not a multiple of the "
                    f"effective prefill chunk {eff} "
                    f"(prefill_chunk={self.prefill_chunk}); chunked "
                    f"prefill windows must tile the bucket exactly")
        if self.prefill_batch < 1:
            raise ValueError(f"prefill_batch must be >= 1, "
                             f"got {self.prefill_batch}")
        if self.prefill_chunks_per_step < 1:
            raise ValueError(f"prefill_chunks_per_step must be >= 1, "
                             f"got {self.prefill_chunks_per_step}")
        if self.prefix_cache_bytes < 0:
            raise ValueError(f"prefix_cache_bytes must be >= 0 "
                             f"(0 disables), got {self.prefix_cache_bytes}")
        if self.kv_layout not in ("bucketed", "paged"):
            raise ValueError(f"kv_layout must be 'bucketed' or 'paged', "
                             f"got {self.kv_layout!r}")
        if self.kv_page_tokens < 0:
            raise ValueError(f"kv_page_tokens must be >= 0 (0 = the "
                             f"effective prefill chunk), "
                             f"got {self.kv_page_tokens}")
        if self.kv_arena_pages < 0:
            raise ValueError(f"kv_arena_pages must be >= 0 (0 = auto), "
                             f"got {self.kv_arena_pages}")
        if self.kv_layout == "paged":
            cap = max(self.decode_buckets)
            pt = self.kv_page_tokens or min(self.prefill_chunk, cap)
            if pt != min(self.prefill_chunk, cap):
                # pages ARE the prefix-trie chunks: a paged prefill chunk
                # fills exactly one page, and a restored trie node maps
                # exactly one page — different granularities would force
                # copy-on-restore back in
                raise ValueError(
                    f"kv_page_tokens {pt} must equal the effective "
                    f"prefill chunk {min(self.prefill_chunk, cap)} in the "
                    f"paged layout (pages are the trie chunks)")
            if cap % pt != 0:
                raise ValueError(
                    f"max decode bucket {cap} is not a multiple of "
                    f"kv_page_tokens {pt}; pages must tile the sequence "
                    f"capacity exactly")
        if self.kv_quant_dtype not in ("none", "int8"):
            raise ValueError(f"kv_quant_dtype must be 'none' or 'int8', "
                             f"got {self.kv_quant_dtype!r}")
        if self.kv_quant_block < 0:
            raise ValueError(f"kv_quant_block must be >= 0 (0 = one block "
                             f"per row), got {self.kv_quant_block}")
        if self.kv_quant_dtype != "none":
            if self.kv_layout != "paged":
                raise ValueError(
                    f"kv_quant_dtype {self.kv_quant_dtype!r} requires the "
                    f"paged layout (quantize-on-commit lives in the page "
                    f"arena), got kv_layout={self.kv_layout!r}")
            if self.kv_cache_dtype != "auto":
                raise ValueError(
                    f"kv_quant_dtype {self.kv_quant_dtype!r} is mutually "
                    f"exclusive with a non-auto kv_cache_dtype "
                    f"({self.kv_cache_dtype!r}): the quantized arena owns "
                    f"its storage dtype (int8 payload + f32 scales)")
        if self.kv_host_tier_bytes < 0:
            raise ValueError(f"kv_host_tier_bytes must be >= 0 "
                             f"(0 disables), got {self.kv_host_tier_bytes}")
        if self.kv_host_tier_bytes:
            if self.kv_layout != "paged":
                raise ValueError(
                    f"kv_host_tier_bytes requires the paged layout (the "
                    f"tier demotes arena pages), got "
                    f"kv_layout={self.kv_layout!r}")
            if not self.enable_prefix_cache or not self.prefix_cache_bytes:
                raise ValueError(
                    "kv_host_tier_bytes requires the prefix cache (the "
                    "tier holds cold TRIE pages; with no trie there is "
                    "nothing to demote)")
        if self.speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0 (0 disables "
                             f"speculation), got {self.speculate_k}")
        if self.speculate_k:
            if self.speculate_drafter not in ("ngram", "draft_model"):
                raise ValueError(
                    f"speculate_drafter must be 'ngram' or 'draft_model', "
                    f"got {self.speculate_drafter!r}")
            # the verify step writes a k+1-row window at a traced start:
            # a window wider than the smallest bucket could NEVER be
            # placed without dynamic_update_slice clamping it onto
            # committed rows, so k+1 must leave headroom in every bucket
            if self.speculate_k + 1 >= min(self.decode_buckets):
                raise ValueError(
                    f"speculate_k {self.speculate_k} leaves no bucket "
                    f"headroom: k+1 ({self.speculate_k + 1}) must be < "
                    f"the smallest decode bucket "
                    f"({min(self.decode_buckets)})")


class ServeEngine:
    """Continuous-batching server over `fn`.

    fn: an easydist `CompiledFunction` (from `easydist_compile`), or a
        plain callable taking BATCHED args — plain callables are wrapped
        with `easydist_compile` unless `compile=False` (useful for tests
        and for pre-jitted functions).
    state: optional leading argument (params pytree) prepended to every
        batched call — keeps model weights a proper jit argument rather
        than a trace constant.
    Requests submit UNBATCHED args; results come back unbatched.
    """

    def __init__(self, fn, config: Optional[ServeConfig] = None, *,
                 state=None, mesh=None, compile: object = "auto",
                 clock: Callable[[], float] = time.monotonic):
        from easydist_tpu.jaxfront.api import CompiledFunction

        self.config = config or ServeConfig()
        self.state = state
        self.clock = clock
        self.metrics = ServeMetrics()
        if isinstance(fn, CompiledFunction):
            self._fn, self._compiled = fn, fn
        elif compile == "auto" or compile is True:
            from easydist_tpu.jaxfront import easydist_compile

            self._fn = easydist_compile(fn, mesh=mesh, state_io={})
            self._compiled = self._fn
        else:
            self._fn, self._compiled = fn, None

        self.queue = RequestQueue(self.config.max_queue)
        self.admission = AdmissionController(
            self.config.max_queue, self.config.default_deadline_ms,
            clock=clock)
        self.batcher = MicroBatcher(
            self.queue, self._execute,
            max_batch_size=max(self.config.batch_buckets),
            max_wait_ms=self.config.max_wait_ms,
            metrics=self.metrics, clock=clock)
        self._disabled_buckets: set = set()
        self._seen_exec_keys: set = set()
        self._started = False
        self.breaker: Optional[CircuitBreaker] = None
        if self.config.breaker_failure_threshold > 0:
            p99_ms = self.config.breaker_p99_threshold_ms
            self.breaker = CircuitBreaker(
                failure_threshold=self.config.breaker_failure_threshold,
                cooldown_s=self.config.breaker_cooldown_ms / 1e3,
                p99_threshold_s=(p99_ms / 1e3 if p99_ms is not None
                                 else None),
                min_samples=self.config.breaker_min_samples,
                p99=lambda: self.metrics.execute.percentile(99),
                clock=clock)
        # watchdog pool: one worker — executions are serial anyway; a
        # timed-out dispatch abandons the whole pool (shutdown(wait=False))
        # so the next batch gets a fresh worker instead of queueing behind
        # the wedged call
        self._watchdog: Optional[ThreadPoolExecutor] = None

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServeEngine":
        self.batcher.start()
        self._started = True
        return self

    def stop(self) -> None:
        self._started = False
        self.batcher.stop()
        if self._watchdog is not None:
            self._watchdog.shutdown(wait=False)
            self._watchdog = None

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------------------------------------------------- submission
    def submit(self, *args, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one unbatched request; returns its result future.
        Raises QueueFullError (backpressure), RequestTooLargeError (no
        bucket fits) or CircuitOpenError (the breaker is shedding)
        synchronously — load shedding happens at the door."""
        self._reject_oversized(args)
        if self.breaker is not None and not self.breaker.allow():
            self.metrics.inc("requests_shed")
            retry_after = self.breaker.retry_after_s()
            raise CircuitOpenError(
                f"circuit open: executor failing persistently; retry in "
                f"{retry_after:.2f}s", retry_after_s=retry_after)
        try:
            self.admission.check_depth(self.queue.depth())
        except QueueFullError:
            self.metrics.inc("requests_rejected")
            raise
        req = Request(args=tuple(args), enqueue_t=self.clock(),
                      deadline_t=self.admission.resolve_deadline(deadline_ms))
        self.metrics.inc("requests_submitted")
        if not self.queue.put(req):  # racing submitters filled it first
            self.metrics.inc("requests_rejected")
            raise QueueFullError(
                f"request queue at capacity ({self.config.max_queue})")
        self.metrics.set_gauge("queue_depth", self.queue.depth())
        return req.future

    def infer(self, *args, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None):
        """Synchronous convenience: submit + wait."""
        return self.submit(*args, deadline_ms=deadline_ms).result(
            timeout=timeout)

    def _reject_oversized(self, args) -> None:
        if self.config.seq_buckets is None:
            return
        cap = max(self.config.seq_buckets)
        for j, a in enumerate(args):
            if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1 \
                    and int(a.shape[0]) > cap:
                raise RequestTooLargeError(
                    f"arg {j} length {int(a.shape[0])} exceeds the largest "
                    f"seq bucket {cap}")

    # ------------------------------------------------------------- warmup
    def warmup(self, example_args: Sequence[object]) -> int:
        """Eagerly compile + run every (batch bucket x seq bucket) shape
        using zero-filled stand-ins shaped like `example_args` (unbatched).
        Returns the number of bucket shapes warmed.  Serving traffic then
        never pays a compile."""
        seqs = self.config.seq_buckets or (None,)
        warmed = 0
        for b in sorted(set(self.config.batch_buckets)):
            if b in self._disabled_buckets:
                continue
            for s in seqs:
                reqs = [Request(args=tuple(
                    self._dummy_arg(a, s) for a in example_args))
                    for _ in range(b)]
                try:
                    # exact serving path (pack -> run), so the signature
                    # cache is warm for real traffic; results discarded
                    batched, meta = pack_requests(
                        reqs, (b,), self.config.seq_buckets,
                        self.config.pad_value)
                    self._run_batched(batched)
                    warmed += 1
                except Exception as e:
                    if is_oom_error(e):
                        self._disable_bucket(b)
                        break
                    raise
        return warmed

    @staticmethod
    def _dummy_arg(example, seq_len):
        if hasattr(example, "shape") and getattr(example, "ndim", 0) >= 1:
            a = np.asarray(example)
            shape = ((seq_len,) if seq_len is not None else a.shape[:1]) \
                + a.shape[1:]
            return np.zeros(shape, dtype=a.dtype)
        return example

    # ------------------------------------------------------------ execution
    def _enabled_buckets(self) -> Tuple[int, ...]:
        out = tuple(b for b in self.config.batch_buckets
                    if b not in self._disabled_buckets)
        if not out:
            raise ServeError(
                "every batch bucket is disabled (all compiles OOMed)")
        return out

    def _disable_bucket(self, bucket: int) -> None:
        self._disabled_buckets.add(bucket)
        self.metrics.inc("oom_degradations")
        logger.warning(
            "[serve] batch bucket %d disabled after device-memory "
            "exhaustion; degrading to buckets %s", bucket,
            sorted(set(self.config.batch_buckets) - self._disabled_buckets))

    def _exec_key(self, batched) -> tuple:
        return tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape")
            else ("scalar", repr(a)) for a in batched)

    def _run_batched(self, batched):
        """One device execution of a packed batch, with executable-cache
        accounting and the optional execute watchdog.  Blocks until the
        result is ready (the scatter needs host values anyway, and
        execute-latency should include it)."""
        if faultinject.fire("serve.oom_bucket"):
            # deterministic stand-in for an XLA compile/alloc failure at
            # this bucket shape — must route through the degrade path
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: injected fake device OOM "
                "(serve.oom_bucket fault point)")
        timeout_ms = self.config.exec_timeout_ms
        if timeout_ms is None:
            return self._dispatch(batched)
        if self._watchdog is None:
            self._watchdog = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="serve-exec")
        fut = self._watchdog.submit(self._dispatch, batched)
        try:
            return fut.result(timeout=timeout_ms / 1e3)
        except FutureTimeoutError:
            # the dispatch cannot be cancelled (no XLA cancellation);
            # abandon the pool — the wedged thread finishes into the void,
            # the next batch gets a fresh worker
            self.metrics.inc("exec_timeouts")
            self._watchdog.shutdown(wait=False)
            self._watchdog = None
            raise ExecTimeoutError(
                f"batch execution exceeded the {timeout_ms:.0f}ms "
                f"watchdog; dispatch abandoned") from None

    def _dispatch(self, batched):
        import jax

        if faultinject.fire("serve.exec_timeout"):
            # simulate a wedged dispatch: sleep well past the watchdog
            t_ms = self.config.exec_timeout_ms
            time.sleep((t_ms * 3 / 1e3) if t_ms is not None else 0.05)
        key = self._exec_key(batched)
        if key in self._seen_exec_keys:
            self.metrics.inc("compile_cache_hits")
        else:
            self.metrics.inc("compile_cache_misses")
            self._seen_exec_keys.add(key)
        call_args = batched if self.state is None \
            else (self.state,) + tuple(batched)
        if self._compiled is not None:
            result = self._compiled.get_compiled(*call_args)
            out = result.tree_jitted(*call_args)
        else:
            out = self._fn(*call_args)
        return jax.block_until_ready(out)

    def _execute(self, reqs) -> None:
        """Batcher callback: pack -> run (retry/degrade) -> scatter."""
        now = self.clock()
        for r in reqs:
            self.metrics.observe("queue_wait", now - r.enqueue_t)
        self._run_group(list(reqs))

    def _run_group(self, reqs) -> None:
        try:
            batched, meta = pack_requests(
                reqs, self._enabled_buckets(), self.config.seq_buckets,
                self.config.pad_value)
        except Exception as e:
            self._fail(reqs, e)
            return

        def attempt():
            return self._run_batched(batched)

        def transient_and_count(exc):
            ok = is_transient_error(exc)
            if ok:
                self.metrics.inc("transient_retries")
            return ok

        # a retry whose backoff outlives every waiter is pure waste: bound
        # the retry loop by the earliest request deadline in the group
        deadlines = [r.deadline_t for r in reqs if r.deadline_t is not None]
        group_deadline = min(deadlines) if deadlines else None

        t0 = self.clock()
        try:
            out = retry_transient(
                attempt, max_retries=self.config.max_retries,
                backoff_s=self.config.retry_backoff_ms / 1e3,
                is_transient=transient_and_count,
                jitter=self.config.retry_jitter,
                deadline_t=group_deadline, clock=self.clock)
        except Exception as e:
            if self.breaker is not None:
                self.breaker.record_failure()
            if is_oom_error(e):
                self._degrade(reqs, meta.batch_bucket, e)
                return
            self._fail(reqs, e)
            return
        if self.breaker is not None:
            self.breaker.record_success()
        self.metrics.record_batch(meta.n_real, meta.batch_bucket,
                                  self.clock() - t0)
        try:
            results = scatter_results(out, meta, self.config.unpad_outputs)
        except Exception as e:
            self._fail(reqs, e)
            return
        done = self.clock()
        for r, res in zip(reqs, results):
            if not r.future.done():
                r.future.set_result(res)
                self.metrics.inc("requests_completed")
                self.metrics.observe("e2e", done - r.enqueue_t)

    def _degrade(self, reqs, failed_bucket: int, exc: Exception) -> None:
        """OOM on `failed_bucket`: disable it and re-pack into the largest
        enabled smaller bucket; no smaller bucket -> the requests fail."""
        self._disable_bucket(failed_bucket)
        smaller = [b for b in self.config.batch_buckets
                   if b < failed_bucket and b not in self._disabled_buckets]
        if not smaller:
            self._fail(reqs, exc)
            return
        cap = max(smaller)
        for i in range(0, len(reqs), cap):
            self._run_group(reqs[i:i + cap])

    def _fail(self, reqs, exc: Exception) -> None:
        self.metrics.inc("requests_failed", len(reqs))
        for r in reqs:
            if not r.future.done():
                r.future.set_exception(exc)

    # ------------------------------------------------------------ reporting
    def stats(self) -> dict:
        """Metrics snapshot + executable-cache state (the e2e acceptance
        surface: compile count == distinct buckets, hit rate > 0)."""
        out = self.metrics.snapshot()
        out["distinct_executables"] = len(self._seen_exec_keys)
        out["disabled_batch_buckets"] = sorted(self._disabled_buckets)
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        if self._compiled is not None:
            out["backend_cache"] = self._compiled.cache_stats()
        return out

    def health(self) -> dict:
        """Liveness/readiness summary for an external health endpoint.

        ready: the engine accepts new work right now (started, circuit not
        open, at least one batch bucket still enabled).
        degraded: serving, but with reduced capability (disabled buckets,
        watchdog timeouts or shed requests observed, half-open circuit).
        """
        breaker_state = (self.breaker.state if self.breaker is not None
                         else "disabled")
        enabled = tuple(b for b in self.config.batch_buckets
                        if b not in self._disabled_buckets)
        m = self.metrics
        ready = bool(self._started and enabled and breaker_state != "open")
        degraded = bool(
            self._disabled_buckets or breaker_state in ("open", "half_open")
            or m.counter("exec_timeouts") or m.counter("requests_shed"))
        return {
            "started": self._started,
            "ready": ready,
            "degraded": degraded,
            "breaker_state": breaker_state,
            "enabled_batch_buckets": list(enabled),
            "disabled_batch_buckets": sorted(self._disabled_buckets),
            "exec_timeouts": m.counter("exec_timeouts"),
            "requests_shed": m.counter("requests_shed"),
            "oom_degradations": m.counter("oom_degradations"),
        }

    def export_metrics(self, db=None, sub_key: Optional[str] = None):
        """Push the snapshot into the runtime PerfDB (serving history lands
        next to EASYDIST_RUNTIME_PROF step times)."""
        name = sub_key or getattr(self._fn, "__name__", "engine")
        return self.metrics.export(db=db, sub_key=name)

    # convenience for bucket-selection introspection/tests
    def bucket_for(self, n_requests: int) -> Optional[int]:
        return select_bucket(n_requests, self._enabled_buckets())
