"""`ServeEngine`: a shape-bucketed continuous-batching endpoint over an
easydist-compiled inference function.

Shape bucketing is the core economics: XLA specializes one executable per
input shape, so unconstrained request shapes would compile per-request.
The engine pads every packed batch up to configured `batch_buckets` x
`seq_buckets`, giving a closed, warmable set of executables — each bucket
compiles exactly once (the `jaxfront` signature cache guarantees it) and
every subsequent request is a cache hit.

Robustness is layered in from `admission.py`: bounded-queue backpressure at
submit, per-request deadlines enforced by the batcher, transient-failure
retry with exponential backoff around execution, and graceful degradation
— a batch bucket whose compile exhausts device memory is disabled and its
requests re-packed into smaller enabled buckets.
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from .admission import (AdmissionController, QueueFullError,
                        RequestTooLargeError, ServeError, is_oom_error,
                        is_transient_error, retry_transient)
from .batcher import (MicroBatcher, Request, RequestQueue, pack_requests,
                      scatter_results, select_bucket)
from .metrics import ServeMetrics

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ServeConfig:
    """Bucketing + batching + admission policy for one engine.

    batch_buckets: allowed padded batch sizes, ascending not required.
    seq_buckets: allowed padded leading-dim lengths for array args (None =
        requests must agree exactly on shapes; only the batch dim pads).
    max_wait_ms: how long the batcher holds the first request of a batch
        open for stragglers (latency floor vs occupancy knob).
    max_queue: bounded queue depth; submits beyond it raise QueueFullError.
    default_deadline_ms: deadline applied when submit() passes none.
    max_retries / retry_backoff_ms: transient-failure policy per batch.
    pad_value: fill for seq padding (e.g. the pad token id).
    unpad_outputs: slice outputs back to each request's original length.
    """
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    seq_buckets: Optional[Tuple[int, ...]] = None
    max_wait_ms: float = 5.0
    max_queue: int = 256
    default_deadline_ms: Optional[float] = None
    max_retries: int = 2
    retry_backoff_ms: float = 10.0
    pad_value: object = 0
    unpad_outputs: bool = True

    def __post_init__(self):
        if not self.batch_buckets:
            raise ValueError("batch_buckets must be non-empty")
        if any(b < 1 for b in self.batch_buckets):
            raise ValueError(f"batch buckets must be >= 1: "
                             f"{self.batch_buckets}")
        if self.seq_buckets is not None and not self.seq_buckets:
            raise ValueError("seq_buckets must be None or non-empty")


class ServeEngine:
    """Continuous-batching server over `fn`.

    fn: an easydist `CompiledFunction` (from `easydist_compile`), or a
        plain callable taking BATCHED args — plain callables are wrapped
        with `easydist_compile` unless `compile=False` (useful for tests
        and for pre-jitted functions).
    state: optional leading argument (params pytree) prepended to every
        batched call — keeps model weights a proper jit argument rather
        than a trace constant.
    Requests submit UNBATCHED args; results come back unbatched.
    """

    def __init__(self, fn, config: Optional[ServeConfig] = None, *,
                 state=None, mesh=None, compile: object = "auto",
                 clock: Callable[[], float] = time.monotonic):
        from easydist_tpu.jaxfront.api import CompiledFunction

        self.config = config or ServeConfig()
        self.state = state
        self.clock = clock
        self.metrics = ServeMetrics()
        if isinstance(fn, CompiledFunction):
            self._fn, self._compiled = fn, fn
        elif compile == "auto" or compile is True:
            from easydist_tpu.jaxfront import easydist_compile

            self._fn = easydist_compile(fn, mesh=mesh, state_io={})
            self._compiled = self._fn
        else:
            self._fn, self._compiled = fn, None

        self.queue = RequestQueue(self.config.max_queue)
        self.admission = AdmissionController(
            self.config.max_queue, self.config.default_deadline_ms,
            clock=clock)
        self.batcher = MicroBatcher(
            self.queue, self._execute,
            max_batch_size=max(self.config.batch_buckets),
            max_wait_ms=self.config.max_wait_ms,
            metrics=self.metrics, clock=clock)
        self._disabled_buckets: set = set()
        self._seen_exec_keys: set = set()
        self._started = False

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServeEngine":
        self.batcher.start()
        self._started = True
        return self

    def stop(self) -> None:
        self._started = False
        self.batcher.stop()

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------------------------------------------------- submission
    def submit(self, *args, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one unbatched request; returns its result future.
        Raises QueueFullError (backpressure) or RequestTooLargeError (no
        bucket fits) synchronously — load shedding happens at the door."""
        self._reject_oversized(args)
        try:
            self.admission.check_depth(self.queue.depth())
        except QueueFullError:
            self.metrics.inc("requests_rejected")
            raise
        req = Request(args=tuple(args), enqueue_t=self.clock(),
                      deadline_t=self.admission.resolve_deadline(deadline_ms))
        self.metrics.inc("requests_submitted")
        if not self.queue.put(req):  # racing submitters filled it first
            self.metrics.inc("requests_rejected")
            raise QueueFullError(
                f"request queue at capacity ({self.config.max_queue})")
        self.metrics.set_gauge("queue_depth", self.queue.depth())
        return req.future

    def infer(self, *args, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None):
        """Synchronous convenience: submit + wait."""
        return self.submit(*args, deadline_ms=deadline_ms).result(
            timeout=timeout)

    def _reject_oversized(self, args) -> None:
        if self.config.seq_buckets is None:
            return
        cap = max(self.config.seq_buckets)
        for j, a in enumerate(args):
            if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1 \
                    and int(a.shape[0]) > cap:
                raise RequestTooLargeError(
                    f"arg {j} length {int(a.shape[0])} exceeds the largest "
                    f"seq bucket {cap}")

    # ------------------------------------------------------------- warmup
    def warmup(self, example_args: Sequence[object]) -> int:
        """Eagerly compile + run every (batch bucket x seq bucket) shape
        using zero-filled stand-ins shaped like `example_args` (unbatched).
        Returns the number of bucket shapes warmed.  Serving traffic then
        never pays a compile."""
        seqs = self.config.seq_buckets or (None,)
        warmed = 0
        for b in sorted(set(self.config.batch_buckets)):
            if b in self._disabled_buckets:
                continue
            for s in seqs:
                reqs = [Request(args=tuple(
                    self._dummy_arg(a, s) for a in example_args))
                    for _ in range(b)]
                try:
                    # exact serving path (pack -> run), so the signature
                    # cache is warm for real traffic; results discarded
                    batched, meta = pack_requests(
                        reqs, (b,), self.config.seq_buckets,
                        self.config.pad_value)
                    self._run_batched(batched)
                    warmed += 1
                except Exception as e:
                    if is_oom_error(e):
                        self._disable_bucket(b)
                        break
                    raise
        return warmed

    @staticmethod
    def _dummy_arg(example, seq_len):
        if hasattr(example, "shape") and getattr(example, "ndim", 0) >= 1:
            a = np.asarray(example)
            shape = ((seq_len,) if seq_len is not None else a.shape[:1]) \
                + a.shape[1:]
            return np.zeros(shape, dtype=a.dtype)
        return example

    # ------------------------------------------------------------ execution
    def _enabled_buckets(self) -> Tuple[int, ...]:
        out = tuple(b for b in self.config.batch_buckets
                    if b not in self._disabled_buckets)
        if not out:
            raise ServeError(
                "every batch bucket is disabled (all compiles OOMed)")
        return out

    def _disable_bucket(self, bucket: int) -> None:
        self._disabled_buckets.add(bucket)
        self.metrics.inc("oom_degradations")
        logger.warning(
            "[serve] batch bucket %d disabled after device-memory "
            "exhaustion; degrading to buckets %s", bucket,
            sorted(set(self.config.batch_buckets) - self._disabled_buckets))

    def _exec_key(self, batched) -> tuple:
        return tuple(
            (tuple(a.shape), str(a.dtype)) if hasattr(a, "shape")
            else ("scalar", repr(a)) for a in batched)

    def _run_batched(self, batched):
        """One device execution of a packed batch, with executable-cache
        accounting.  Blocks until the result is ready (the scatter needs
        host values anyway, and execute-latency should include it)."""
        import jax

        key = self._exec_key(batched)
        if key in self._seen_exec_keys:
            self.metrics.inc("compile_cache_hits")
        else:
            self.metrics.inc("compile_cache_misses")
            self._seen_exec_keys.add(key)
        call_args = batched if self.state is None \
            else (self.state,) + tuple(batched)
        if self._compiled is not None:
            result = self._compiled.get_compiled(*call_args)
            out = result.tree_jitted(*call_args)
        else:
            out = self._fn(*call_args)
        return jax.block_until_ready(out)

    def _execute(self, reqs) -> None:
        """Batcher callback: pack -> run (retry/degrade) -> scatter."""
        now = self.clock()
        for r in reqs:
            self.metrics.observe("queue_wait", now - r.enqueue_t)
        self._run_group(list(reqs))

    def _run_group(self, reqs) -> None:
        try:
            batched, meta = pack_requests(
                reqs, self._enabled_buckets(), self.config.seq_buckets,
                self.config.pad_value)
        except Exception as e:
            self._fail(reqs, e)
            return

        def attempt():
            return self._run_batched(batched)

        def transient_and_count(exc):
            ok = is_transient_error(exc)
            if ok:
                self.metrics.inc("transient_retries")
            return ok

        t0 = self.clock()
        try:
            out = retry_transient(
                attempt, max_retries=self.config.max_retries,
                backoff_s=self.config.retry_backoff_ms / 1e3,
                is_transient=transient_and_count)
        except Exception as e:
            if is_oom_error(e):
                self._degrade(reqs, meta.batch_bucket, e)
                return
            self._fail(reqs, e)
            return
        self.metrics.record_batch(meta.n_real, meta.batch_bucket,
                                  self.clock() - t0)
        try:
            results = scatter_results(out, meta, self.config.unpad_outputs)
        except Exception as e:
            self._fail(reqs, e)
            return
        done = self.clock()
        for r, res in zip(reqs, results):
            if not r.future.done():
                r.future.set_result(res)
                self.metrics.inc("requests_completed")
                self.metrics.observe("e2e", done - r.enqueue_t)

    def _degrade(self, reqs, failed_bucket: int, exc: Exception) -> None:
        """OOM on `failed_bucket`: disable it and re-pack into the largest
        enabled smaller bucket; no smaller bucket -> the requests fail."""
        self._disable_bucket(failed_bucket)
        smaller = [b for b in self.config.batch_buckets
                   if b < failed_bucket and b not in self._disabled_buckets]
        if not smaller:
            self._fail(reqs, exc)
            return
        cap = max(smaller)
        for i in range(0, len(reqs), cap):
            self._run_group(reqs[i:i + cap])

    def _fail(self, reqs, exc: Exception) -> None:
        self.metrics.inc("requests_failed", len(reqs))
        for r in reqs:
            if not r.future.done():
                r.future.set_exception(exc)

    # ------------------------------------------------------------ reporting
    def stats(self) -> dict:
        """Metrics snapshot + executable-cache state (the e2e acceptance
        surface: compile count == distinct buckets, hit rate > 0)."""
        out = self.metrics.snapshot()
        out["distinct_executables"] = len(self._seen_exec_keys)
        out["disabled_batch_buckets"] = sorted(self._disabled_buckets)
        if self._compiled is not None:
            out["backend_cache"] = self._compiled.cache_stats()
        return out

    def export_metrics(self, db=None, sub_key: Optional[str] = None):
        """Push the snapshot into the runtime PerfDB (serving history lands
        next to EASYDIST_RUNTIME_PROF step times)."""
        name = sub_key or getattr(self._fn, "__name__", "engine")
        return self.metrics.export(db=db, sub_key=name)

    # convenience for bucket-selection introspection/tests
    def bucket_for(self, n_requests: int) -> Optional[int]:
        return select_bucket(n_requests, self._enabled_buckets())
