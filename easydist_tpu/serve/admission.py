"""Admission control and robustness policies for the serving engine.

Three concerns live here, all independent of how batches are packed or run:

- backpressure: a bounded queue rejects (`QueueFullError`) instead of
  buffering unboundedly — the caller sheds load or retries upstream;
- deadlines: every request carries an absolute expiry; an expired request
  surfaces `DeadlineExceededError` instead of occupying a batch slot;
- failure policy: transient executor failures are retried with jittered
  exponential backoff (`retry_transient`) that never sleeps past the
  request's deadline, and a bucket whose compile exhausts device memory is
  classified by `is_oom_error` so the engine can degrade to smaller batch
  buckets rather than failing every request routed to it;
- degradation signals: `ExecTimeoutError` (the execute watchdog fired) and
  `CircuitOpenError` (the breaker is shedding load, with a retry-after
  hint) give clients STRUCTURED failure they can route on, instead of a
  hang or an opaque stack.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional


class ServeError(Exception):
    """Base class for serving-layer failures."""


class QueueFullError(ServeError):
    """Backpressure: the bounded request queue is at capacity."""


class DeadlineExceededError(ServeError):
    """The request's deadline expired before a result was produced."""


class EngineStoppedError(ServeError):
    """The engine was stopped while the request was still pending."""


class RequestTooLargeError(ServeError):
    """A request dimension exceeds the largest configured bucket."""


class ReplicaDrainingError(ServeError):
    """The session is draining (scale-down in progress): it retires its
    in-flight work but admits nothing new.  A fleet router routes the
    request to another replica; a direct caller should back off."""


class ExecTimeoutError(ServeError):
    """One device execution exceeded the per-batch watchdog deadline.
    The dispatch itself cannot be cancelled (XLA has no cancellation); the
    engine abandons the wedged call on its worker thread and fails the
    batch so clients stop waiting."""


class CircuitOpenError(ServeError):
    """The engine's circuit breaker is OPEN: the executor is persistently
    failing (or browned out on latency) and load is shed at the door.
    `retry_after_s` hints when the breaker will next admit a probe."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        self.retry_after_s = retry_after_s
        super().__init__(msg)


def is_oom_error(exc: BaseException) -> bool:
    """Device-memory exhaustion during compile or execution (XLA surfaces
    it as RESOURCE_EXHAUSTED; allocators say "out of memory")."""
    msg = str(exc).lower()
    return ("resource_exhausted" in msg or "out of memory" in msg
            or "allocation failure" in msg or type(exc).__name__ == "OomError")


def is_transient_error(exc: BaseException) -> bool:
    """Failures worth retrying: runtime hiccups (UNAVAILABLE/ABORTED RPC
    states, connection resets), never programming errors or OOM — retrying
    an OOM at the same shape just re-exhausts the device."""
    if is_oom_error(exc):
        return False
    if isinstance(exc, (TypeError, ValueError, KeyError, AttributeError,
                        ServeError)):
        return False
    msg = str(exc).lower()
    return any(tok in msg for tok in (
        "unavailable", "aborted", "deadline_exceeded", "connection reset",
        "transient", "cancelled", "socket closed"))


def retry_transient(fn: Callable, *, max_retries: int, backoff_s: float,
                    is_transient: Callable[[BaseException], bool]
                    = is_transient_error,
                    sleep: Callable[[float], None] = time.sleep,
                    jitter: float = 0.0,
                    deadline_t: Optional[float] = None,
                    clock: Callable[[], float] = time.monotonic,
                    rng: Callable[[], float] = random.random):
    """Call `fn()` retrying transient failures with jittered exponential
    backoff (backoff_s, 2*backoff_s, 4*backoff_s, ..., each stretched by up
    to `jitter` fraction — synchronized retry storms from many batchers
    hitting one wedged device are worse than the failure itself).

    Non-transient failures and the final attempt's failure propagate.  With
    `deadline_t` (absolute `clock()` seconds), a retry whose backoff would
    land past the deadline is NOT taken: the prior failure propagates
    immediately — sleeping through the caller's deadline to deliver a
    result nobody is waiting for helps no one."""
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classification decides
            if attempt >= max_retries or not is_transient(e):
                raise
            delay = backoff_s * (2 ** attempt)
            if jitter:
                delay *= 1.0 + jitter * rng()
            if deadline_t is not None and clock() + delay >= deadline_t:
                raise
            sleep(delay)
            attempt += 1


class AdmissionController:
    """Admission decision at submit time: assign the absolute deadline and
    enforce queue-depth backpressure.  Kept separate from the queue so the
    policy is unit-testable without threads."""

    def __init__(self, max_queue: int,
                 default_deadline_ms: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.default_deadline_ms = default_deadline_ms
        self.clock = clock

    def resolve_deadline(self, deadline_ms: Optional[float]) -> Optional[float]:
        """Relative deadline (ms, or None for the config default) ->
        absolute monotonic expiry seconds (None = no deadline)."""
        ms = deadline_ms if deadline_ms is not None else self.default_deadline_ms
        if ms is None:
            return None
        return self.clock() + ms / 1e3

    def check_depth(self, depth: int) -> None:
        if depth >= self.max_queue:
            raise QueueFullError(
                f"request queue at capacity ({self.max_queue}); shed load "
                f"or raise ServeConfig.max_queue")
