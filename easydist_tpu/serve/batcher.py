"""Continuous micro-batching: queue, pack, scatter.

Requests carry UNBATCHED args (one example each).  The batcher drains the
queue under `max_batch_size`/`max_wait_ms`, pads heterogeneous requests to
a common bucket shape (leading dim of every rank>=1 arg -> the smallest
configured seq bucket that fits the longest request; batch -> the smallest
batch bucket that fits the drained count), stacks them into one device
batch, and scatters results back to per-request futures.

Padding policy: seq padding replicates `pad_value`; batch padding repeats
the last real row (finite values by construction — a NaN-poisoned pad row
could otherwise infect reductions).  Outputs are un-padded by slicing any
leading output dim that equals the padded seq length back to the request's
original length (`unpad_outputs`).

The pure functions (`select_bucket`, `pack_requests`, `scatter_results`)
are the unit-test surface; `MicroBatcher` only adds the thread + clock.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .admission import (DeadlineExceededError, EngineStoppedError,
                        RequestTooLargeError)


@dataclass
class Request:
    """One queued inference request: unbatched args + its result future."""
    args: Tuple[object, ...]
    future: Future = field(default_factory=Future)
    enqueue_t: float = 0.0
    deadline_t: Optional[float] = None  # absolute monotonic seconds

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t

    def shape_class(self) -> tuple:
        """Requests pack together only when they agree on everything but
        the leading (seq) dim of each array arg."""
        sig = []
        for a in self.args:
            if hasattr(a, "shape") and getattr(a, "ndim", 0) >= 1:
                sig.append(("arr", tuple(a.shape[1:]), str(a.dtype)))
            else:
                sig.append(("scalar", type(a).__name__))
        return tuple(sig)


class RequestQueue:
    """Thread-safe bounded FIFO with a batching drain: block for the first
    request, then collect more until `max_n` or `max_wait_s` elapses."""

    def __init__(self, max_depth: int):
        self.max_depth = max_depth
        self._items: List[Request] = []
        self._cond = threading.Condition()

    def depth(self) -> int:
        with self._cond:
            return len(self._items)

    def put(self, req: Request) -> bool:
        """False when full (caller raises QueueFullError — admission owns
        the policy; the queue only reports capacity)."""
        with self._cond:
            if len(self._items) >= self.max_depth:
                return False
            self._items.append(req)
            self._cond.notify()
            return True

    def drain(self, max_n: int, max_wait_s: float,
              stop: threading.Event,
              clock: Callable[[], float] = time.monotonic) -> List[Request]:
        """Up to `max_n` requests: waits (interruptibly) for the first,
        then keeps the window open `max_wait_s` for stragglers.  Returns
        [] when `stop` is set and the queue is empty."""
        with self._cond:
            while not self._items:
                if stop.is_set():
                    return []
                self._cond.wait(timeout=0.05)
            deadline = clock() + max_wait_s
            while len(self._items) < max_n and not stop.is_set():
                remaining = deadline - clock()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
            got = self._items[:max_n]
            del self._items[:max_n]
            return got

    def drain_all(self) -> List[Request]:
        with self._cond:
            got, self._items = self._items, []
            return got


def select_bucket(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds every bucket."""
    fitting = [b for b in buckets if b >= n]
    return min(fitting) if fitting else None


@dataclass
class PackMeta:
    """Everything scatter needs to undo the packing."""
    n_real: int
    batch_bucket: int
    # per request: per arg, the original leading length (None for scalars
    # and for args that were not padded)
    orig_lens: List[Tuple[Optional[int], ...]]
    padded_lens: Tuple[Optional[int], ...]  # per arg, the bucketed length


def _pad_leading(arr: np.ndarray, target: int, pad_value) -> np.ndarray:
    if arr.shape[0] == target:
        return arr
    widths = [(0, target - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths, constant_values=pad_value)


def pack_requests(reqs: Sequence[Request],
                  batch_buckets: Sequence[int],
                  seq_buckets: Optional[Sequence[int]],
                  pad_value=0) -> Tuple[Tuple[np.ndarray, ...], PackMeta]:
    """Pad + stack same-shape-class requests into one bucketed batch.

    Raises RequestTooLargeError when the drained count exceeds the largest
    batch bucket (the batcher's drain cap should prevent this) or a seq
    length exceeds the largest seq bucket.  With `seq_buckets=None`, all
    requests must agree exactly on every arg shape (batch-only padding).
    """
    if not reqs:
        raise ValueError("pack_requests needs at least one request")
    n = len(reqs)
    batch_bucket = select_bucket(n, batch_buckets)
    if batch_bucket is None:
        raise RequestTooLargeError(
            f"{n} requests exceed the largest batch bucket "
            f"{max(batch_buckets)}")

    n_args = len(reqs[0].args)
    padded_lens: List[Optional[int]] = []
    for j in range(n_args):
        vals = [r.args[j] for r in reqs]
        if not (hasattr(vals[0], "shape") and getattr(vals[0], "ndim", 0) >= 1):
            if any(v != vals[0] for v in vals[1:]):
                raise ValueError(
                    f"scalar arg {j} differs across packed requests")
            padded_lens.append(None)
            continue
        lens = [int(v.shape[0]) for v in vals]
        if seq_buckets is None:
            if len(set(lens)) != 1:
                raise ValueError(
                    f"arg {j} has heterogeneous leading dims {sorted(set(lens))} "
                    f"but no seq_buckets are configured")
            padded_lens.append(None)
            continue
        target = select_bucket(max(lens), seq_buckets)
        if target is None:
            raise RequestTooLargeError(
                f"arg {j} length {max(lens)} exceeds the largest seq "
                f"bucket {max(seq_buckets)}")
        padded_lens.append(target)

    batched = []
    for j in range(n_args):
        if padded_lens[j] is None and not (
                hasattr(reqs[0].args[j], "shape")
                and getattr(reqs[0].args[j], "ndim", 0) >= 1):
            batched.append(reqs[0].args[j])  # shared scalar, not batched
            continue
        rows = []
        for r in reqs:
            a = np.asarray(r.args[j])
            if padded_lens[j] is not None:
                a = _pad_leading(a, padded_lens[j], pad_value)
            rows.append(a)
        # batch padding repeats the last real row: finite by construction
        rows.extend([rows[-1]] * (batch_bucket - n))
        batched.append(np.stack(rows, axis=0))

    orig_lens = []
    for r in reqs:
        row = []
        for j, a in enumerate(r.args):
            if padded_lens[j] is not None:
                row.append(int(a.shape[0]))
            else:
                row.append(None)
        orig_lens.append(tuple(row))
    meta = PackMeta(n_real=n, batch_bucket=batch_bucket,
                    orig_lens=orig_lens, padded_lens=tuple(padded_lens))
    return tuple(batched), meta


def scatter_results(outputs, meta: PackMeta,
                    unpad_outputs: bool = True) -> List[object]:
    """Split a batched output pytree back into per-request results.

    Every output leaf's leading axis is the batch; row i belongs to request
    i.  When un-padding, a row dim that equals a padded seq length is
    sliced back to that request's original length for that arg (arg 0 wins
    when several args share the padded length — the conventional
    "first arg is the sequence" layout)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(outputs)
    per_req: List[object] = []
    pad_targets = [(j, t) for j, t in enumerate(meta.padded_lens)
                   if t is not None]
    for i in range(meta.n_real):
        rows = []
        for leaf in leaves:
            row = np.asarray(leaf)[i]
            if unpad_outputs and getattr(row, "ndim", 0) >= 1:
                for j, target in pad_targets:
                    if row.shape[0] == target \
                            and meta.orig_lens[i][j] is not None:
                        row = row[: meta.orig_lens[i][j]]
                        break
            rows.append(row)
        per_req.append(jax.tree_util.tree_unflatten(treedef, rows))
    return per_req


class MicroBatcher:
    """Background drain loop: queue -> groups by shape class -> executor.

    `execute(requests)` (the engine) owns padding, running, and resolving
    futures; the batcher owns timing, grouping, and deadline expiry so the
    engine never sees an expired request."""

    def __init__(self, queue: RequestQueue, execute, *,
                 max_batch_size: int, max_wait_ms: float,
                 metrics=None, clock: Callable[[], float] = time.monotonic):
        self.queue = queue
        self.execute = execute
        self.max_batch_size = max_batch_size
        self.max_wait_s = max_wait_ms / 1e3
        self.metrics = metrics
        self.clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="easydist-serve-batcher",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        for req in self.queue.drain_all():
            if not req.future.done():
                req.future.set_exception(
                    EngineStoppedError("engine stopped before execution"))

    def expire(self, reqs: List[Request]) -> List[Request]:
        """Fail expired requests; return the still-live ones."""
        now = self.clock()
        live = []
        for r in reqs:
            if r.expired(now):
                if not r.future.done():
                    r.future.set_exception(DeadlineExceededError(
                        f"deadline expired {1e3 * (now - r.deadline_t):.1f}ms "
                        f"ago while queued"))
                if self.metrics is not None:
                    self.metrics.inc("requests_timed_out")
            else:
                live.append(r)
        return live

    def _loop(self):
        while not self._stop.is_set():
            reqs = self.queue.drain(self.max_batch_size, self.max_wait_s,
                                    self._stop, clock=self.clock)
            if self.metrics is not None:
                self.metrics.set_gauge("queue_depth", self.queue.depth())
            reqs = self.expire(reqs)
            if not reqs:
                continue
            # group by shape class, preserving arrival order within groups
            groups: dict = {}
            for r in reqs:
                groups.setdefault(r.shape_class(), []).append(r)
            for group in groups.values():
                try:
                    self.execute(group)
                except Exception as e:  # executor must not kill the loop
                    for r in group:
                        if not r.future.done():
                            r.future.set_exception(e)
