"""Continuous-batching inference serving over easydist-compiled functions.

The first request-shaped (rather than step-shaped) layer of the stack:
`ServeEngine` turns any easydist-compiled inference function into a served
endpoint with a shape-bucketed executable cache, a continuous micro-batcher
draining a bounded request queue, admission control (backpressure, deadlines,
jittered transient-failure retry, OOM bucket degradation), degradation
machinery (execute watchdog, circuit breaker, `health()` readiness), and
serving metrics exported through the runtime PerfDB.

The reference (alibaba/easydist) has no serving layer — see docs/SERVING.md
and the AoiZora/DistIR pointers in PAPERS.md for why an auto-parallel
framework pays off at inference time behind a dispatch layer like this.
"""

from .admission import (CircuitOpenError, DeadlineExceededError,  # noqa: F401
                        EngineStoppedError, ExecTimeoutError,
                        QueueFullError, ReplicaDrainingError,
                        RequestTooLargeError, ServeError,
                        is_oom_error, is_transient_error, retry_transient)
from .batcher import (MicroBatcher, PackMeta, Request,  # noqa: F401
                      RequestQueue, pack_requests, scatter_results,
                      select_bucket)
from .engine import ServeConfig, ServeEngine  # noqa: F401
from .generation import GenerationSession, kv_cache_specs  # noqa: F401
from .metrics import LatencyHistogram, ServeMetrics  # noqa: F401
from .prefix_cache import PrefixCache, chunk_key  # noqa: F401
