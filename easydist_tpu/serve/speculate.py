"""Speculative decoding: drafters + the greedy accept rule.

The serving loop (serve/generation.py) pays one full target-model forward
per emitted token — single-stream latency is bounded by sequential
decode.  Speculative decoding breaks the bound without touching the
output: a cheap drafter proposes k tokens, ONE batched target-model
verify step (`models/*.py::*_verify_step*`) scores all k+1 positions in a
fixed-shape program, and the session commits the longest prefix the
target model itself would have produced.  The accept rule is
self-validating under greedy decoding — position i's verify logits equal
what sequential decode would produce whenever positions < i carry the
true sequence, so every committed token is exactly the plain-greedy
token REGARDLESS of where the drafts came from.  Drafters therefore only
affect speed (acceptance rate), never output; `speculate_k=0` and any
drafter produce identical streams.

Two built-in drafters:

  * `NGramDrafter` — zero-cost self-speculative prompt lookup: find the
    most recent earlier occurrence of the sequence's own trailing n-gram
    and propose the tokens that followed it.  Free (no model, no device
    work), surprisingly strong on repetitive text (code, templated
    prose, retrieval-augmented prompts that quote their context).
  * `SmallModelDrafter` — a second, smaller model's cached greedy decode
    kept in sync with each request's committed sequence by
    teacher-forced steps.  Proposals are a pure function of the
    committed token prefix (greedy draft model), so a crash-resumed
    request re-drafts identically — fleet recovery stays bitwise.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["NGramDrafter", "SmallModelDrafter", "accept_length"]


def accept_length(draft: Sequence[int], target: Sequence[int]) -> int:
    """Number of draft tokens accepted: the length of the longest prefix
    where draft[i] == target[i].  The round commits target[0..n]
    INCLUSIVE (n = the returned count) — the first n committed tokens
    ratify accepted drafts, the (n+1)-th is the target model's own
    correction (or bonus token on full acceptance), so every round emits
    at least one token and never advances past the first mismatch
    (analyze rule SERVE003's bookkeeping arm audits exactly this)."""
    n = 0
    for d, t in zip(draft, target):
        if int(d) != int(t):
            break
        n += 1
    return n


class NGramDrafter:
    """Prompt-lookup drafting over the request's own emitted+prompt ids.

    `propose` looks up the most recent PRIOR occurrence of the
    sequence's trailing n-gram (longest n first, `max_ngram` down to
    `min_ngram`) that has k following tokens, and proposes those tokens
    (falling back to a truncated tail match only when no occurrence is
    k deep).  Proposals are a pure function of the token sequence — the
    per-request n-gram position index is only an accelerator and is
    rebuilt whenever the sequence is not an extension of what was
    indexed, so a crash-resumed request (prompt' = prompt + accepted
    ids) re-drafts identically.  `propose` runs on the host inside
    every scheduling round, so its cost rides the decode critical path:
    the index makes it O(new tokens) per call instead of a full
    right-to-left rescan."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min_ngram={min_ngram}, max_ngram={max_ngram}")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # request_id -> (indexed ids copy, {ngram tuple: [positions]})
        self._index: Dict[int, tuple] = {}

    def _positions(self, request_id: int, ids: List[int]):
        """The request's n-gram position index, extended (or rebuilt on
        a prefix mismatch) to cover `ids`."""
        st = self._index.get(request_id)
        if st is not None:
            seen, idx = st
            if len(seen) > len(ids) or seen != ids[:len(seen)]:
                st = None
        if st is None:
            seen, idx = [], {}
            self._index[request_id] = (seen, idx)
        n_ids = len(ids)
        for n in range(self.min_ngram, self.max_ngram + 1):
            for i in range(max(0, len(seen) - n + 1), n_ids - n + 1):
                idx.setdefault(tuple(ids[i:i + n]), []).append(i)
        seen.extend(ids[len(seen):])
        return idx

    def propose(self, request_id: int, ids: Sequence[int],
                k: int) -> Optional[List[int]]:
        """Up to `k` proposed continuation tokens for the sequence
        `ids`, or None when no trailing n-gram recurs."""
        ids = list(ids)
        n_ids = len(ids)
        index = self._positions(request_id, ids)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if n_ids < n + 1:
                continue
            occ = index.get(tuple(ids[n_ids - n:]))
            if not occ:
                continue
            # most recent prior occurrence WITH k continuation tokens:
            # on cyclic text the most recent match always sits near the
            # tail, where the continuation is truncated by the end of
            # the sequence — an earlier full-depth match proposes k
            # tokens where the tail match proposes one or two.  The
            # truncated most-recent match is kept as a fallback when no
            # occurrence has k following tokens.
            fallback = None
            for i in reversed(occ):
                if i == n_ids - n:
                    continue  # the trailing n-gram itself
                cont = ids[i + n:i + n + k]
                if len(cont) == k:
                    return cont
                if cont and fallback is None:
                    fallback = cont
            if fallback is not None:
                return fallback
        return None

    def forget(self, request_id: int) -> None:
        """Drop the request's position index (proposals are a pure
        function of the sequence; this only frees the accelerator)."""
        self._index.pop(request_id, None)


class SmallModelDrafter:
    """Draft-model drafting: a second cached greedy forward (the same
    `model_decode(params, cache, token, pos) -> (cache, logits)` contract
    `GenerationSession` uses, batch=1) teacher-forced along each
    request's committed sequence.

    Per round: roll the per-request cursor back to the longest common
    prefix of what was fed and what is now committed (stale cache rows
    past the cursor are masked by the position-based attention and
    overwritten on re-feed — the same rewind rule the target cache
    uses), feed the newly committed tokens, then autoregressively
    propose k draft tokens.  With acceptance rate a, sync costs ~1-2
    teacher-forced steps per round.  ONE compiled signature total (the
    batch=1 cache shape is fixed)."""

    def __init__(self, params, *, model_decode: Callable,
                 init_cache: Callable, max_len: int, mesh=None):
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2, got {max_len}")
        self.params = params
        self.max_len = max_len
        self._init_cache = init_cache
        self._mesh = mesh
        self._states: Dict[int, dict] = {}

        def _step(cache, params, token, pos):
            import jax.numpy as jnp

            cache, logits = model_decode(params, cache, token, pos)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._step_def = _step
        self._step_c = None

    def _step_compiled(self):
        if self._step_c is None:
            from easydist_tpu.jaxfront import easydist_compile

            self._step_c = easydist_compile(self._step_def,
                                            mesh=self._mesh)
        return self._step_c

    def _feed(self, st: dict, token: int, pos: int) -> int:
        import jax.numpy as jnp
        import numpy as np

        st["cache"], nxt = self._step_compiled()(
            st["cache"], self.params,
            jnp.asarray([token], jnp.int32), jnp.asarray([pos], jnp.int32))
        return int(np.asarray(nxt)[0])

    def propose(self, request_id: int, ids: Sequence[int],
                k: int) -> Optional[List[int]]:
        ids = [int(t) for t in ids]
        st = self._states.get(request_id)
        if st is None:
            st = {"cache": self._init_cache(1, self.max_len), "fed": []}
            self._states[request_id] = st
        fed = st["fed"]
        common = 0
        for a, b in zip(fed, ids):
            if a != b:
                break
            common += 1
        seq = list(ids)
        nxt = None
        for pos in range(common, len(seq)):        # teacher-forced sync
            if pos >= self.max_len:
                st["fed"] = seq[:self.max_len]
                return None
            nxt = self._feed(st, seq[pos], pos)
        if nxt is None:                            # nothing new to feed:
            if not seq:                            # re-derive from cache
                return None
            pos = len(seq) - 1
            nxt = self._feed(st, seq[pos], pos)
        proposals = [nxt]
        while len(proposals) < k and len(seq) + len(proposals) < self.max_len:
            seqpos = len(seq) + len(proposals) - 1
            nxt = self._feed(st, proposals[-1], seqpos)
            proposals.append(nxt)
        st["fed"] = seq + proposals[:-1]
        return proposals

    def forget(self, request_id: int) -> None:
        self._states.pop(request_id, None)
