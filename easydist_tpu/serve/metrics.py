"""Serving observability: counters, gauges, latency histograms.

Everything a dashboard needs to judge a serving deployment — queue depth,
batch occupancy (real rows / bucket rows), executable-cache hit rate,
p50/p95/p99 latency — collected lock-cheap in-process and exported through
the existing runtime plumbing (`runtime.perfdb.PerfDB`), so serving history
lands next to the step-time history `EASYDIST_RUNTIME_PROF` already keeps.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

# log-spaced bucket upper bounds, 0.1ms .. ~107s (x2 per bucket)
_DEFAULT_BOUNDS = tuple(1e-4 * (2 ** i) for i in range(21))


class LatencyHistogram:
    """Fixed log-spaced histogram over seconds.  Percentiles resolve to the
    upper bound of the bucket containing the rank — a <=2x overestimate by
    construction, stable under any traffic shape, O(1) memory."""

    def __init__(self, bounds=_DEFAULT_BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1 overflow bucket
        self.total = 0
        self.sum = 0.0

    def observe(self, seconds: float) -> None:
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if seconds <= b:
                idx = i
                break
        self.counts[idx] += 1
        self.total += 1
        self.sum += seconds

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100] -> seconds (bucket upper bound), None when empty."""
        if self.total == 0:
            return None
        rank = max(1, int(round(p / 100.0 * self.total)))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1] * 2
        return self.bounds[-1] * 2

    def mean(self) -> Optional[float]:
        return self.sum / self.total if self.total else None

    def snapshot(self) -> Dict[str, float]:
        out = {"count": self.total}
        if self.total:
            out.update(mean_s=self.mean(),
                       p50_s=self.percentile(50),
                       p95_s=self.percentile(95),
                       p99_s=self.percentile(99))
        return out


class ServeMetrics:
    """Thread-safe counters/gauges/histograms for one `ServeEngine`.

    Counter names (all monotonically increasing):
      requests_submitted / completed / failed / timed_out / rejected /
      shed (circuit open), batches_executed, batch_rows_real,
      batch_rows_padded, compile_cache_hits, compile_cache_misses,
      oom_degradations, transient_retries, exec_timeouts (watchdog),
      tokens_generated (decode steps x active slots).
    Chunked-prefill counters: prefills (admissions), prefill_chunks
      (batched chunk calls), prefill_tokens_real (prompt tokens actually
      needing prefill, prefix reuse already deducted),
      prefill_tokens_padded (executed token slots = rows x chunk per
      call), prefix_tokens_reused / prefix_tokens_total,
      prefix_cache_hits / misses / evictions (trie chunk events).
    Speculative-decoding counters (serve/speculate.py): verify_steps,
      draft_tokens_proposed / draft_tokens_accepted,
      speculative_rollback_pages_released (paged rollback returns);
      gauge acceptance_rate (lifetime accepted / proposed).
    Gauges: decode_slot_occupancy (active slots / total slots at the last
      decode step), prefill_padding_ratio (executed token slots per real
      prefill token, 1.0 = zero waste), prefix_cache_hit_rate (fraction
      of prompt tokens restored from the prefix trie).
    Histograms: queue_wait (submit->drain), execute (device time incl.
    host roundtrip), e2e (submit->future resolution), per_token (one
    decode-step wall time, all slots), ttft (submit->first token)."""

    def __init__(self, replica_id: Optional[str] = None):
        # fleet label: stamped into every snapshot and the default PerfDB
        # sub_key so N replicas' histories never collide under one key
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._prompt_hist: Dict[int, int] = {}  # prompt_len -> admissions
        self.queue_wait = LatencyHistogram()
        self.execute = LatencyHistogram()
        self.e2e = LatencyHistogram()
        self.per_token = LatencyHistogram()
        self.ttft = LatencyHistogram()

    # ------------------------------------------------------------- recording
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, hist_name: str, seconds: float) -> None:
        with self._lock:
            getattr(self, hist_name).observe(seconds)

    def record_batch(self, n_real: int, bucket: int,
                     execute_s: float) -> None:
        with self._lock:
            self._counters["batches_executed"] = \
                self._counters.get("batches_executed", 0) + 1
            self._counters["batch_rows_real"] = \
                self._counters.get("batch_rows_real", 0) + n_real
            self._counters["batch_rows_padded"] = \
                self._counters.get("batch_rows_padded", 0) + bucket
            self.execute.observe(execute_s)

    def record_decode_step(self, n_active: int, n_slots: int,
                           step_s: float) -> None:
        """One token step across the whole slot pool: `n_active` slots
        produced a real token, `n_slots` rows executed either way."""
        with self._lock:
            self._counters["tokens_generated"] = \
                self._counters.get("tokens_generated", 0) + n_active
            self._counters["decode_steps"] = \
                self._counters.get("decode_steps", 0) + 1
            self._gauges["decode_slot_occupancy"] = \
                (n_active / n_slots) if n_slots else 0.0
            self.per_token.observe(step_s)

    def record_speculation(self, proposed: int, accepted: int,
                           committed: int, n_ran: int, n_slots: int,
                           step_s: float,
                           pages_released: int = 0) -> None:
        """One speculative verify round (serve/speculate.py): `proposed`
        draft tokens entered the verify step, `accepted` of them were
        ratified, `committed` tokens were emitted in total (accepted
        drafts + one correction/bonus per slot — these count toward
        `tokens_generated` exactly like decode-step tokens, since they
        ARE the plain-greedy tokens).  `n_ran` slots rode the verify
        program out of `n_slots` rows; `pages_released` arena pages were
        returned by the paged rollback.  `acceptance_rate` is the
        lifetime accepted/proposed ratio — the drafter-quality signal
        (speedup ~ committed tokens per verify step)."""
        with self._lock:
            self._counters["verify_steps"] = \
                self._counters.get("verify_steps", 0) + 1
            self._counters["draft_tokens_proposed"] = \
                self._counters.get("draft_tokens_proposed", 0) + proposed
            self._counters["draft_tokens_accepted"] = \
                self._counters.get("draft_tokens_accepted", 0) + accepted
            self._counters["tokens_generated"] = \
                self._counters.get("tokens_generated", 0) + committed
            if pages_released:
                self._counters["speculative_rollback_pages_released"] = \
                    self._counters.get(
                        "speculative_rollback_pages_released", 0) \
                    + pages_released
            total = self._counters["draft_tokens_proposed"]
            if total:
                self._gauges["acceptance_rate"] = \
                    self._counters["draft_tokens_accepted"] / total
            self._gauges["decode_slot_occupancy"] = \
                (n_ran / n_slots) if n_slots else 0.0
            self.per_token.observe(step_s)

    def record_admission(self, prompt_len: int, prefix_len: int) -> None:
        """One prompt admitted into the chunked-prefill scheduler:
        `prefix_len` of its `prompt_len` tokens were restored from the
        prefix trie, the rest must run through prefill."""
        with self._lock:
            self._counters["prefills"] = \
                self._counters.get("prefills", 0) + 1
            # prompt-length histogram (exact counts per length) — what
            # sim/capacity.py::TrafficSpec.from_metrics reconstructs its
            # prompt distribution from
            self._prompt_hist[prompt_len] = \
                self._prompt_hist.get(prompt_len, 0) + 1
            self._counters["prefill_tokens_real"] = \
                self._counters.get("prefill_tokens_real", 0) \
                + (prompt_len - prefix_len)
            self._counters["prefix_tokens_reused"] = \
                self._counters.get("prefix_tokens_reused", 0) + prefix_len
            total = self._counters["prefix_tokens_total"] = \
                self._counters.get("prefix_tokens_total", 0) + prompt_len
            self._gauges["prefix_cache_hit_rate"] = \
                self._counters["prefix_tokens_reused"] / total

    def record_prefill_chunk(self, n_rows: int, chunk: int,
                             chunk_s: float) -> None:
        """One batched chunk call: `n_rows` staging rows executed `chunk`
        token slots each (idle rows and padded tails included — that IS
        the waste the padding-ratio gauge measures)."""
        with self._lock:
            self._counters["prefill_chunks"] = \
                self._counters.get("prefill_chunks", 0) + 1
            padded = self._counters["prefill_tokens_padded"] = \
                self._counters.get("prefill_tokens_padded", 0) \
                + n_rows * chunk
            real = self._counters.get("prefill_tokens_real", 0)
            if real:
                self._gauges["prefill_padding_ratio"] = padded / real
            self.execute.observe(chunk_s)

    def record_kv_pool(self, pages_in_use: int, mapped_tokens: int,
                       page_tokens: int,
                       quant_bytes_saved: Optional[int] = None) -> None:
        """Paged-KV pool occupancy: `pages_in_use` arena pages are live
        (slot-mapped or trie-held) holding `mapped_tokens` real tokens of
        `pages_in_use * page_tokens` capacity.  `kv_page_utilization` is
        the intra-page fill fraction — 1.0 means zero fragmentation, and
        (1 - it) is the only padding waste the paged layout CAN have
        (the bucketed pool pads every row to the bucket instead).
        `quant_bytes_saved` is HBM the live pages did NOT spend versus
        model-precision storage (block-scaled int8 payload + scales vs
        model dtype) — the quantized arena's density win, exported to
        the PerfDB with every snapshot."""
        with self._lock:
            self._gauges["kv_pages_in_use"] = pages_in_use
            cap = pages_in_use * page_tokens
            self._gauges["kv_page_utilization"] = \
                (mapped_tokens / cap) if cap else 1.0
            if quant_bytes_saved is not None:
                self._gauges["kv_quant_bytes_saved"] = quant_bytes_saved

    def record_copy_on_restore_saved(self, nbytes: int) -> None:
        """A prefix restore mapped `nbytes` of committed pages into a
        sequence's page table instead of `dynamic_update_slice`-copying
        them — the zero-copy-restore contract, measured."""
        with self._lock:
            self._counters["copy_on_restore_bytes_saved"] = \
                self._counters.get("copy_on_restore_bytes_saved", 0) + nbytes

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    # ------------------------------------------------------------- reporting
    def batch_occupancy(self) -> Optional[float]:
        """Mean fraction of bucket rows carrying real requests — the
        padding waste signal (1.0 = every executed row was real work)."""
        with self._lock:
            padded = self._counters.get("batch_rows_padded", 0)
            real = self._counters.get("batch_rows_real", 0)
        return real / padded if padded else None

    def compile_cache_hit_rate(self) -> Optional[float]:
        with self._lock:
            h = self._counters.get("compile_cache_hits", 0)
            m = self._counters.get("compile_cache_misses", 0)
        return h / (h + m) if (h + m) else None

    def prefill_padding_ratio(self) -> Optional[float]:
        """Executed prefill token slots per real prefill token (>= 1.0;
        1.0 = every executed slot carried a real token)."""
        with self._lock:
            padded = self._counters.get("prefill_tokens_padded", 0)
            real = self._counters.get("prefill_tokens_real", 0)
        return padded / real if real else None

    def prefix_cache_hit_rate(self) -> Optional[float]:
        """Fraction of submitted prompt tokens restored from the prefix
        trie instead of recomputed."""
        with self._lock:
            reused = self._counters.get("prefix_tokens_reused", 0)
            total = self._counters.get("prefix_tokens_total", 0)
        return reused / total if total else None

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            prompt_hist = dict(self._prompt_hist)
            hists = {"queue_wait": self.queue_wait.snapshot(),
                     "execute": self.execute.snapshot(),
                     "e2e": self.e2e.snapshot(),
                     "per_token": self.per_token.snapshot(),
                     "ttft": self.ttft.snapshot()}
        return {"replica_id": self.replica_id,
                "counters": counters, "gauges": gauges,
                "prompt_hist": prompt_hist,
                "latency": hists,
                "batch_occupancy": self.batch_occupancy(),
                "compile_cache_hit_rate": self.compile_cache_hit_rate(),
                "prefill_padding_ratio": self.prefill_padding_ratio(),
                "prefix_cache_hit_rate": self.prefix_cache_hit_rate()}

    def export(self, db=None, key: str = "serving",
               sub_key: Optional[str] = None, persist: bool = True):
        """Record the snapshot into the persistent PerfDB (the same store
        runtime profiling uses), appended to a bounded history list.  The
        default sub_key carries the replica label ("engine[r1]") so fleet
        replicas keep separate histories."""
        if db is None:
            from easydist_tpu.runtime.perfdb import PerfDB

            db = PerfDB()
        if sub_key is None:
            sub_key = (f"engine[{self.replica_id}]" if self.replica_id
                       else "engine")
        db.append_history(key, sub_key, self.snapshot())
        if persist:
            try:
                db.persist()
            except Exception:  # metrics export must never fail serving
                pass
        return db
